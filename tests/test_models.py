"""Model-family tests: forward shapes, loss decrease, TP-rule alignment."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    LlamaConfig,
    LlamaForCausalLM,
    ResNet,
    ResNetConfig,
    causal_lm_loss,
    make_bert_loss_fn,
    make_llama_loss_fn,
)


def test_llama_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    logits = model.apply(params, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_llama_gqa_and_causality():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.randint(0, 255, (1, 12)), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    logits_full = model.apply(params, ids)
    # causality: changing a future token must not change past logits
    ids2 = ids.at[0, 8].set((ids[0, 8] + 1) % 255)
    logits_mod = model.apply(params, ids2)
    np.testing.assert_allclose(
        np.asarray(logits_full[0, :8]), np.asarray(logits_mod[0, :8]), rtol=2e-2, atol=2e-3
    )
    assert not np.allclose(np.asarray(logits_full[0, 8:]), np.asarray(logits_mod[0, 8:]), atol=1e-3)


def test_llama_trains_under_accelerator():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ids = jnp.ones((8, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    state = acc.create_train_state(params, optax.adamw(1e-3), apply_fn=model.apply)
    step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
    rng = np.random.default_rng(0)
    batch_np = rng.integers(0, 255, (8, 16))
    from accelerate_tpu.ops import host_local_to_global
    from jax.sharding import PartitionSpec as P

    batch = host_local_to_global(
        {"input_ids": batch_np.astype(np.int32), "labels": batch_np.astype(np.int32)},
        acc.mesh, P(("dp_shard",), None),
    )
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_llama_tp_sharding_applied():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    ids = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    state = acc.create_train_state(params, optax.sgd(1e-3))
    q_kernel = state.params["params"]["layers_0"]["self_attn"]["q_proj"]["kernel"]
    assert "tp" in str(q_kernel.sharding.spec)
    logits = model.apply(state.params, ids)  # still computes correctly sharded
    assert logits.shape == (4, 16, cfg.vocab_size)


def test_causal_lm_loss_ignore_index():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.asarray([[1, 2, -100, 3]])
    loss = causal_lm_loss(logits, labels)
    assert np.isclose(float(loss), np.log(8), rtol=1e-5)


def test_bert_forward_and_train():
    cfg = BertConfig.tiny()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((4, 16), jnp.int32)
    mask = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids, mask)
    logits = model.apply(params, ids, mask)
    assert logits.shape == (4, cfg.num_labels)

    acc = Accelerator()
    state = acc.create_train_state(params, optax.adamw(1e-3))
    step = acc.prepare_train_step(make_bert_loss_fn(model))
    batch = {
        "input_ids": jnp.asarray(np.random.randint(0, 500, (8, 16)), jnp.int32),
        "attention_mask": jnp.ones((8, 16), jnp.int32),
        "labels": jnp.asarray(np.random.randint(0, 2, (8,)), jnp.int32),
    }
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_resnet_forward():
    cfg = ResNetConfig.tiny()
    model = ResNet(cfg)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    logits, updates = model.apply(variables, x, mutable=["batch_stats"])
    assert logits.shape == (2, 10)
    assert "batch_stats" in updates


def test_flops_per_token_positive():
    from accelerate_tpu.models import flops_per_token

    cfg = LlamaConfig.llama2_7b()
    f = flops_per_token(cfg, 4096)
    # 6*6.7e9 ~ 4e10 plus attention term
    assert 3.5e10 < f < 6e10


# ---------------------------------------------------------------------------
# T5 encoder-decoder (reference Megatron T5TrainStep megatron_lm.py:718)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_t5_forward_shapes():
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration

    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    enc_ids = jnp.ones((2, 12), jnp.int32)
    dec_ids = jnp.ones((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), enc_ids, dec_ids)
    logits = model.apply(params, enc_ids, dec_ids)
    assert logits.shape == (2, 8, cfg.vocab_size)


@pytest.mark.slow
def test_t5_decoder_is_causal():
    """Changing a future decoder token must not change earlier logits."""
    import numpy as np

    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration

    cfg = T5Config.tiny(dtype=jnp.float32)
    model = T5ForConditionalGeneration(cfg)
    enc = jnp.ones((1, 8), jnp.int32)
    dec = jnp.arange(8, dtype=jnp.int32)[None] % cfg.vocab_size
    params = model.init(jax.random.key(0), enc, dec)
    base = model.apply(params, enc, dec)
    dec2 = dec.at[0, -1].set((int(dec[0, -1]) + 1) % cfg.vocab_size)
    pert = model.apply(params, enc, dec2)
    np.testing.assert_allclose(np.asarray(base[0, :-1]), np.asarray(pert[0, :-1]), atol=1e-5)


@pytest.mark.slow
def test_t5_encoder_mask_blocks_attention():
    import numpy as np

    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration

    cfg = T5Config.tiny(dtype=jnp.float32)
    model = T5ForConditionalGeneration(cfg)
    enc = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    dec = jnp.ones((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), enc, dec)
    mask = jnp.asarray([[True, True, False, False]])
    masked = model.apply(params, enc, dec, attention_mask=mask)
    # tokens behind the mask must not influence the output
    enc2 = enc.at[0, 2].set(99)
    masked2 = model.apply(params, enc2, dec, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(masked), np.asarray(masked2), atol=1e-5)


@pytest.mark.slow
def test_t5_training_converges_sharded():
    """Seq2seq copy task improves under dp_shard x tp sharding."""
    import optax

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration, make_t5_loss_fn

    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2),
        mixed_precision="bf16",
    )
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(2, cfg.vocab_size, (8, 12)), jnp.int32)
    batch = {"input_ids": src, "labels": src}  # copy task

    params = model.init(jax.random.key(0), src[:, :4], src[:, :4])
    state = acc.create_train_state(params, optax.adamw(3e-3), apply_fn=model.apply)
    step = acc.prepare_train_step(make_t5_loss_fn(model), max_grad_norm=1.0)

    first = None
    for _ in range(8):
        state, metrics = step(state, batch)
        first = first or float(metrics["loss"])
    assert float(metrics["loss"]) < first, (first, float(metrics["loss"]))


@pytest.mark.slow
def test_t5_ffn_kernels_are_tensor_parallel_sharded():
    """Regression: wi_gate/wi_up must match the TP rule table so the d_model x
    d_ff FFN matrices actually shard over tp (not silently replicate)."""
    from accelerate_tpu import ParallelismConfig
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration
    from accelerate_tpu.parallel.sharding import TRANSFORMER_TP_RULES, make_sharding_plan

    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    enc = jnp.ones((1, 4), jnp.int32)
    abstract = jax.eval_shape(lambda: model.init(jax.random.key(0), enc, enc))
    pcfg = ParallelismConfig(dp_shard_size=4, tp_size=2)
    plan = make_sharding_plan(abstract, pcfg.build_device_mesh(), pcfg, tp_rules=TRANSFORMER_TP_RULES)
    mlp = plan["params"]["enc_layers_0"]["mlp"]
    assert mlp["wi_gate"]["kernel"].spec[-1] == "tp", mlp["wi_gate"]["kernel"].spec
    assert mlp["wi_up"]["kernel"].spec[-1] == "tp", mlp["wi_up"]["kernel"].spec
    assert mlp["wo_mlp"]["kernel"].spec[0] == "tp", mlp["wo_mlp"]["kernel"].spec


@pytest.mark.slow
def test_llama_remat_policy_dots_compiles():
    """remat_policy='dots' (save matmul outputs) must trace/execute like 'full'."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn

    cfg = LlamaConfig.tiny(remat=True, remat_policy="dots")
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    acc = Accelerator()
    params = model.init(jax.random.key(0), ids)
    state = acc.create_train_state(params, optax.sgd(0.1), apply_fn=model.apply)
    step = acc.prepare_train_step(make_llama_loss_fn(model))
    state, metrics = step(state, {"input_ids": ids, "labels": ids})
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
@pytest.mark.parametrize("tied_cases", [(False,), (True,)])
def test_fused_linear_xent_matches_logits_path(tied_cases):
    """Chunked fused linear+CE (ops/fused_xent.py) == logits path: loss and
    every gradient leaf, tied and untied heads, with ignore_index masking.
    Whole-model compiles x2 put both cases in the slow tier; the fast tier
    keeps the op-level grads check (test_fused_linear_xent_non_divisible_
    vocab) and the on-chip bench selftest exercises the kernel for real."""
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn

    for tied in tied_cases:
        cfg = LlamaConfig.tiny(dtype=jnp.float32, tie_word_embeddings=tied)
        model = LlamaForCausalLM(cfg)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 24)), jnp.int32)
        labels = ids.at[0, :5].set(-100)  # exercise the mask
        params = model.init(jax.random.key(0), ids)
        batch = {"input_ids": ids, "labels": labels}

        base = make_llama_loss_fn(model)
        fused = make_llama_loss_fn(model, fused_vocab_chunks=4)
        l0, g0 = jax.value_and_grad(base)(params, batch)
        l1, g1 = jax.value_and_grad(fused)(params, batch)
        assert abs(float(l0) - float(l1)) < 1e-4, (tied, float(l0), float(l1))
        flat1 = {jax.tree_util.keystr(p): v for p, v in jax.tree_util.tree_flatten_with_path(g1)[0]}
        for p, v in jax.tree_util.tree_flatten_with_path(g0)[0]:
            key = jax.tree_util.keystr(p)
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(flat1[key]), atol=2e-4, err_msg=f"tied={tied} {key}"
            )


@pytest.mark.slow
def test_fused_linear_xent_non_divisible_vocab():
    """Vocab not divisible by num_chunks (clamped-slice regression): loss and
    grads must still match the reference exactly."""
    from accelerate_tpu.ops.fused_xent import fused_linear_xent

    rng = np.random.default_rng(1)
    N, H, V = 6, 8, 10
    h = jnp.asarray(rng.standard_normal((N, H)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, H)) * 0.3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, N), jnp.int32)
    mask = jnp.asarray([True] * 5 + [False])

    def ref(h, w):
        logits = h @ w.T
        lse = jax.nn.logsumexp(logits, -1)
        ll = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
        return jnp.sum((lse - ll) * mask) / jnp.sum(mask)

    l_r, g_r = jax.value_and_grad(ref, argnums=(0, 1))(h, w)
    for nc in (3, 4, 7):
        l_f, g_f = jax.value_and_grad(
            lambda h, w: fused_linear_xent(h, w, labels, mask, nc, True), argnums=(0, 1)
        )(h, w)
        assert abs(float(l_f) - float(l_r)) < 1e-5, (nc, float(l_f), float(l_r))
        np.testing.assert_allclose(np.asarray(g_f[0]), np.asarray(g_r[0]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(g_f[1]), np.asarray(g_r[1]), atol=1e-5)


@pytest.mark.slow
def test_t5_remat_matches_plain():
    """remat=True changes memory, not math: same logits and grads."""
    import numpy as np

    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration
    from accelerate_tpu.models.t5 import make_t5_loss_fn

    enc = jnp.ones((1, 8), jnp.int32)
    dec = jnp.arange(8, dtype=jnp.int32)[None] % 256
    plain = T5ForConditionalGeneration(T5Config.tiny(dtype=jnp.float32))
    remat = T5ForConditionalGeneration(T5Config.tiny(dtype=jnp.float32, remat=True))
    params = plain.init(jax.random.key(0), enc, dec)
    np.testing.assert_allclose(
        np.asarray(remat.apply(params, enc, dec)),
        np.asarray(plain.apply(params, enc, dec)), atol=1e-5,
    )
    batch = {"input_ids": enc, "labels": dec}
    g1 = jax.grad(make_t5_loss_fn(plain))(params, batch)
    g2 = jax.grad(make_t5_loss_fn(remat))(params, batch)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_offload_remat_policy_degrades_and_trains(monkeypatch):
    """remat_policy="offload" (activation boundaries in pinned host memory
    on TPU) keeps param paths and numerics; on the CPU mesh it degrades to
    full remat, so this pins structure + gradient flow + loss parity — and
    then forces the real _stack branch (host_offload_supported patched
    True) to pin its param-path parity too."""
    from accelerate_tpu.models import make_llama_loss_fn

    cfg = LlamaConfig.tiny(remat=True, remat_policy="offload")
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    assert "layers_0" in params["params"] and "layers_1" in params["params"]
    loss_fn = make_llama_loss_fn(model)
    loss, grads = jax.value_and_grad(loss_fn)(params, {"input_ids": ids, "labels": ids})
    assert all(bool(jnp.isfinite(g).all()) for g in jax.tree_util.tree_leaves(grads))
    ref_cfg = LlamaConfig.tiny(remat=True, remat_policy="full")
    ref = make_llama_loss_fn(LlamaForCausalLM(ref_cfg))(params, {"input_ids": ids, "labels": ids})
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    # the real offload branch (the nn.remat'd _stack function) must produce
    # the same param structure — a scoping regression would otherwise only
    # surface on TPU hardware at checkpoint load
    monkeypatch.setattr(
        "accelerate_tpu.parallel.sharding.host_offload_supported", lambda: True
    )
    params_stack = model.init(jax.random.PRNGKey(0), ids)
    assert jax.tree_util.tree_structure(params_stack) == jax.tree_util.tree_structure(params)
    loss_stack = loss_fn(params, {"input_ids": ids, "labels": ids})
    np.testing.assert_allclose(float(loss_stack), float(ref), rtol=1e-5)


@pytest.mark.slow
def test_scan_layers_matches_unrolled():
    """scan_layers=True computes the same function as the unrolled stack:
    init the unrolled model, stack its per-layer params into the scan
    layout, and require identical logits + loss gradients (remat on, the
    131k-config shape: remat_policy degrades to full on CPU)."""
    from accelerate_tpu.models.llama import stack_layer_params, unstack_layer_params

    cfg = LlamaConfig.tiny(remat=True, remat_policy="offload", dtype=jnp.float32)
    scan_cfg = LlamaConfig.tiny(remat=True, remat_policy="offload", scan_layers=True,
                                dtype=jnp.float32)
    model, scan_model = LlamaForCausalLM(cfg), LlamaForCausalLM(scan_cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 255, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    stacked = stack_layer_params(params)
    k = stacked["params"]["layers_scan"]["block"]["self_attn"]["q_proj"]["kernel"]
    assert k.shape[0] == cfg.num_hidden_layers

    np.testing.assert_allclose(
        np.asarray(model.apply(params, ids)),
        np.asarray(scan_model.apply(stacked, ids)), rtol=2e-5, atol=2e-5)

    loss_fn = make_llama_loss_fn(model)
    scan_loss_fn = make_llama_loss_fn(scan_model)
    batch = {"input_ids": ids, "labels": ids}
    loss, grads = jax.value_and_grad(loss_fn)(params, batch)
    s_loss, s_grads = jax.value_and_grad(scan_loss_fn)(stacked, batch)
    np.testing.assert_allclose(float(loss), float(s_loss), rtol=1e-5)
    # grads in the scan layout unstack back to the unrolled layout
    for (pa, ga), (pb, gb) in zip(
        sorted(jax.tree_util.tree_flatten_with_path(grads)[0], key=lambda t: str(t[0])),
        sorted(jax.tree_util.tree_flatten_with_path(unstack_layer_params(s_grads))[0],
               key=lambda t: str(t[0])),
    ):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=5e-3, atol=2e-4,
                                   err_msg=str(pa))

    # round-trip
    rt = unstack_layer_params(stacked)
    assert jax.tree_util.tree_structure(rt) == jax.tree_util.tree_structure(params)


def test_boundary_offload_fraction_is_identity_math():
    """The hybrid boundary-residency split (boundary_offload_fraction < 1,
    docs/long_context.md) is slice+concat inside the scan body — pure
    placement, so logits and grads must match the frac=1.0 scan model
    exactly.  (On the bench rig the split measurably did NOT move the
    T>=131,072 crash wall — the knob is kept for hosts where pinned is the
    genuine binding pool; this pins that it can never change numerics.)"""
    from accelerate_tpu.models.llama import stack_layer_params

    base = LlamaConfig.tiny(remat=True, remat_policy="offload", scan_layers=True,
                            dtype=jnp.float32)
    split = LlamaConfig.tiny(remat=True, remat_policy="offload", scan_layers=True,
                             boundary_offload_fraction=0.5, dtype=jnp.float32)
    m_base, m_split = LlamaForCausalLM(base), LlamaForCausalLM(split)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 255, (2, 16)), jnp.int32)
    unrolled = LlamaForCausalLM(LlamaConfig.tiny(dtype=jnp.float32))
    stacked = stack_layer_params(unrolled.init(jax.random.PRNGKey(0), ids))

    np.testing.assert_array_equal(
        np.asarray(m_base.apply(stacked, ids)), np.asarray(m_split.apply(stacked, ids)))
    batch = {"input_ids": ids, "labels": ids}
    l_a, g_a = jax.value_and_grad(make_llama_loss_fn(m_base))(stacked, batch)
    l_b, g_b = jax.value_and_grad(make_llama_loss_fn(m_split))(stacked, batch)
    assert float(l_a) == float(l_b)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        g_a, g_b)


def test_boundary_offload_fraction_validation():
    with pytest.raises(ValueError, match="boundary_offload_fraction"):
        LlamaConfig.tiny(boundary_offload_fraction=0.0)
    with pytest.raises(ValueError, match="boundary_offload_fraction"):
        LlamaConfig.tiny(boundary_offload_fraction=1.5)


@pytest.mark.slow
def test_scan_layers_init_and_tp_sharding():
    """Direct init in the scan layout + the sharding planner's shifted TP
    rules: the stacked q_proj kernel [L, H, H'] shards 'tp' on its LAST dim."""
    cfg = LlamaConfig.tiny(scan_layers=True)
    model = LlamaForCausalLM(cfg)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    ids = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    import optax as _optax

    state = acc.create_train_state(params, _optax.sgd(1e-3))
    k = state.params["params"]["layers_scan"]["block"]["self_attn"]["q_proj"]["kernel"]
    assert k.ndim == 3
    assert "tp" in str(k.sharding.spec)
    assert k.sharding.spec[2] == "tp" or k.sharding.spec[-1] == "tp"
    logits = model.apply(state.params, ids)
    assert logits.shape == (4, 16, cfg.vocab_size)


def test_scan_layers_cached_decode_raises():
    """scan_layers has no cached-decode path; the error must say how to
    convert (unstack + scan_layers=False) instead of a scope lookup crash."""
    from accelerate_tpu.models.llama import init_cache

    cfg = LlamaConfig.tiny(scan_layers=True)
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    cache = init_cache(cfg, 1, 16)
    with pytest.raises(ValueError, match="unstack_layer_params"):
        model.apply(params, ids, cache=cache)


@pytest.mark.slow
def test_scan_block_size_matches_unrolled():
    """scan_block_size=2 (pair iterations, halved offload boundaries)
    computes the same function as the unrolled stack; converters map
    global layer i to (iteration i//bs, slot i%bs) and round-trip."""
    from accelerate_tpu.models.llama import stack_layer_params, unstack_layer_params

    cfg = LlamaConfig.tiny(num_hidden_layers=4, remat=True, remat_policy="offload",
                           dtype=jnp.float32)
    scan_cfg = LlamaConfig.tiny(num_hidden_layers=4, remat=True, remat_policy="offload",
                                scan_layers=True, scan_block_size=2, dtype=jnp.float32)
    model, scan_model = LlamaForCausalLM(cfg), LlamaForCausalLM(scan_cfg)
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 255, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    stacked = stack_layer_params(params, scan_block_size=2)
    blk = stacked["params"]["layers_scan"]
    assert set(blk) == {"block_0", "block_1"}
    assert blk["block_0"]["self_attn"]["q_proj"]["kernel"].shape[0] == 2

    np.testing.assert_allclose(
        np.asarray(model.apply(params, ids)),
        np.asarray(scan_model.apply(stacked, ids)), rtol=2e-5, atol=2e-5)

    loss_fn, s_loss_fn = make_llama_loss_fn(model), make_llama_loss_fn(scan_model)
    batch = {"input_ids": ids, "labels": ids}
    loss = loss_fn(params, batch)
    s_loss, s_grads = jax.value_and_grad(s_loss_fn)(stacked, batch)
    np.testing.assert_allclose(float(loss), float(s_loss), rtol=1e-5)
    assert all(bool(jnp.isfinite(g).all()) for g in jax.tree_util.tree_leaves(s_grads))

    rt = unstack_layer_params(stacked)
    assert jax.tree_util.tree_structure(rt) == jax.tree_util.tree_structure(params)
    for a, b in zip(jax.tree_util.tree_leaves(rt), jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="scan_block_size"):
        LlamaConfig.tiny(num_hidden_layers=4, scan_layers=True, scan_block_size=3)
    with pytest.raises(ValueError, match="requires scan_layers"):
        LlamaConfig.tiny(num_hidden_layers=4, scan_block_size=2)


@pytest.mark.slow
def test_mixtral_scan_layers_parity():
    """scan_layers composes with the MoE block family (MixtralConfig
    subclasses LlamaConfig; blocks are homogeneous so the stacked scan
    applies unchanged)."""
    from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM
    from accelerate_tpu.models.llama import stack_layer_params

    cfg = MixtralConfig.tiny(dtype=jnp.float32)
    scfg = MixtralConfig.tiny(dtype=jnp.float32, scan_layers=True)
    m, sm = MixtralForCausalLM(cfg), MixtralForCausalLM(scfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 255, (2, 16)), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), ids)
    np.testing.assert_allclose(
        np.asarray(m.apply(params, ids)),
        np.asarray(sm.apply(stack_layer_params(params), ids)),
        rtol=2e-5, atol=2e-5)
