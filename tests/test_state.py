"""Tests for state singletons (mirror of reference tests/test_state_checkpointing
+ test_accelerator state behaviors)."""

import jax
import numpy as np
import pytest

from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.parallelism_config import ParallelismConfig
from accelerate_tpu.utils.dataclasses import DistributedType, GradientAccumulationPlugin


def test_partial_state_singleton():
    s1 = PartialState()
    s2 = PartialState()
    assert s1.__dict__ is s2.__dict__
    assert s1.num_devices == len(jax.devices())
    assert s1.num_processes == 1
    assert s1.is_main_process
    assert s1.distributed_type in (DistributedType.MULTI_DEVICE, DistributedType.NO)


def test_partial_state_reset():
    s = PartialState()
    assert s.initialized
    PartialState._reset_state()
    # borg dict is shared: clearing it de-initializes existing instances too
    assert not s.initialized
    s2 = PartialState()
    assert s2.initialized


def test_accelerator_state_mixed_precision():
    state = AcceleratorState(mixed_precision="bf16")
    assert state.mixed_precision == "bf16"
    # borg: second construction keeps first config
    state2 = AcceleratorState()
    assert state2.mixed_precision == "bf16"


def test_accelerator_state_invalid_precision():
    with pytest.raises(ValueError):
        AcceleratorState(mixed_precision="int3")


def test_accelerator_state_default_mesh():
    state = AcceleratorState()
    mesh = state.mesh
    assert mesh.devices.size == len(jax.devices())
    assert mesh.shape["dp_shard"] == len(jax.devices())


def test_state_delegation():
    state = AcceleratorState()
    assert state.num_processes == 1
    assert state.is_main_process
    assert state.device is jax.local_devices()[0]


def test_split_between_processes_single():
    s = PartialState()
    with s.split_between_processes([1, 2, 3]) as inputs:
        assert inputs == [1, 2, 3]


def test_main_process_first():
    s = PartialState()
    with s.main_process_first():
        pass  # single process: no deadlock, no-op barrier


def test_on_main_process_decorator():
    s = PartialState()
    calls = []

    @s.on_main_process
    def fn(x):
        calls.append(x)
        return x

    fn(5)
    assert calls == [5]


def test_gradient_state():
    gs = GradientState(GradientAccumulationPlugin(num_steps=4))
    assert gs.num_steps == 4
    assert gs.sync_gradients
    assert not gs.end_of_dataloader
    assert gs.remainder == -1
    gs2 = GradientState()
    assert gs2.num_steps == 4  # borg
    gs._set_sync_gradients(False)
    assert not gs2.sync_gradients


def test_gradient_accumulation_plugin_validation():
    with pytest.raises(ValueError):
        GradientAccumulationPlugin(num_steps=0)
    with pytest.raises(ValueError):
        GradientAccumulationPlugin(mode="bogus")


def test_failed_init_does_not_poison_singleton():
    """A construction that fails validation must roll the borg state back:
    the user's corrected retry gets a clean init, not 'already initialized
    with a different parallelism_config' (or a silently skipped
    mixed_precision check)."""
    AcceleratorState._reset_state(reset_partial_state=True)
    with pytest.raises(ValueError):
        AcceleratorState(parallelism_config=ParallelismConfig(cp_size=2, sp_size=2))
    with pytest.raises(ValueError, match="mixed_precision"):
        AcceleratorState(mixed_precision="fp4")
    # corrected retry succeeds with the requested config
    st = AcceleratorState(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    assert st.mesh.shape["tp"] == 2
    AcceleratorState._reset_state(reset_partial_state=True)
