"""Int8 optimizer state with SR requantization (ops/int8_state.py) — the
host-byte floor of the offload ladder (docs/performance.md).  Pins: the
blockwise quant round-trips within its scale bound, SR requant is unbiased
(linear map in value space, log map in log space), the -sr8 optimizers track
their fp32 references, nu survives where nearest rounding freezes, the optax
delta contract reconstructs bitwise, and int8 state + scales round-trip
through save_state/load_state bit-exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.ops.int8_state import (
    LOG_RANGE_BITS,
    adamw_int8_sr,
    dequantize_int8_blockwise,
    dequantize_u8_log_blockwise,
    int8_scale_shape,
    lion_int8_sr,
    quantize_int8_blockwise,
    quantize_u8_log_blockwise,
)


# ---------------------------------------------------------------------------
# quant/dequant primitives
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(512,), (8, 64), (100,), (1,), (3, 5), (130,)])
def test_int8_linear_roundtrip_error_bound(shape):
    """Nearest round-trip error is at most half a code step per element
    (step = block absmax / 127), for divisible and non-divisible shapes."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    codes, scales = quantize_int8_blockwise(x, 128)
    assert codes.shape == x.shape and codes.dtype == jnp.int8
    assert scales.shape == int8_scale_shape(shape, 128)
    back = dequantize_int8_blockwise(codes, scales, 128)
    err = np.abs(np.asarray(back) - np.asarray(x)).max()
    assert err <= 0.5 * float(np.asarray(scales).max()) + 1e-7, err


def test_u8_log_roundtrip_relative_error():
    """The log map holds ~one-code *relative* accuracy across orders of
    magnitude — the property the linear map lacks and the second moment
    needs (a denominator must never round to hard zero)."""
    rng = np.random.default_rng(1)
    # 6 decades of dynamic range inside each block
    v = jnp.asarray((10.0 ** rng.uniform(-6, 0, (1024,))).astype(np.float32))
    codes, scales = quantize_u8_log_blockwise(v, 128)
    assert codes.dtype == jnp.uint8
    back = np.asarray(dequantize_u8_log_blockwise(codes, scales, 128))
    # half-code multiplicative step: 2^(LOG_RANGE_BITS/255/2)
    factor = 2.0 ** (LOG_RANGE_BITS / 255.0 / 2.0) * 1.001
    ratio = back / np.asarray(v)
    assert ratio.max() <= factor and ratio.min() >= 1.0 / factor, (
        ratio.min(), ratio.max(), factor)
    assert (back > 0).all()  # never a hard zero

    # exact zeros decode to the map floor (absmax * 2^-24), not garbage
    z = jnp.concatenate([jnp.zeros((64,), jnp.float32), jnp.ones((64,), jnp.float32)])
    zc, zs = quantize_u8_log_blockwise(z, 128)
    zb = np.asarray(dequantize_u8_log_blockwise(zc, zs, 128))
    assert zb[:64].max() <= 2.0 ** -LOG_RANGE_BITS * 1.001


def test_int8_sr_requant_is_unbiased():
    """E[dequant(SR-quant(x))] = x over independent salts (linear map)."""
    x = jnp.full((2048,), 0.31337, jnp.float32)
    rng = np.random.default_rng(2)
    ent = jnp.asarray(rng.normal(size=(2048,)).astype(np.float32))
    acc = 0.0
    n = 200
    for s in range(n):
        c, sc = quantize_int8_blockwise(
            x, 128, salt=jnp.uint32((s * 2654435761) & 0xFFFFFFFF), entropy=ent)
        acc += float(np.asarray(dequantize_int8_blockwise(c, sc, 128)).mean())
    # one code step is absmax/127 ~ 0.0025; the SR mean must sit well
    # inside it
    assert abs(acc / n - 0.31337) < 3e-4, acc / n


def test_u8_log_sr_requant_is_unbiased_in_log_space():
    """The log map's SR dithers the *code*, so the geometric mean (E[log v])
    is what it preserves."""
    x = jnp.full((2048,), 0.0123, jnp.float32)
    # anchor the block scale with one absmax element per block so the
    # tested value sits mid-map
    x = x.at[::128].set(1.0)
    rng = np.random.default_rng(3)
    ent = jnp.asarray(rng.normal(size=(2048,)).astype(np.float32))
    acc = 0.0
    n = 200
    mask = np.ones(2048, bool)
    mask[::128] = False
    for s in range(n):
        c, sc = quantize_u8_log_blockwise(
            x, 128, salt=jnp.uint32((s * 40503) & 0xFFFFFFFF), entropy=ent)
        back = np.asarray(dequantize_u8_log_blockwise(c, sc, 128))
        acc += np.log2(back[mask]).mean()
    # one code is ~0.094 in log2; the SR mean must sit well inside it
    assert abs(acc / n - np.log2(0.0123)) < 0.02, (acc / n, np.log2(0.0123))


def test_sr8_codes_bounded_and_absmax_stable():
    """SR never pushes a code out of range, and the block-absmax element
    (whose code is exactly ±qmax) never moves."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(512,)).astype(np.float32))
    peak = int(np.abs(np.asarray(x)).argmax())
    for s in range(16):
        c, sc = quantize_int8_blockwise(
            x, 512, salt=jnp.uint32(s + 1), entropy=x)
        cn = np.asarray(c, np.int32)
        assert cn.max() <= 127 and cn.min() >= -127
        assert abs(cn[peak]) == 127


# ---------------------------------------------------------------------------
# the -sr8 optimizers
# ---------------------------------------------------------------------------


def test_lion_sr8_tracks_fp32_lion():
    """Convergence parity on a regression: bf16 SR params + int8 momentum
    reach the same loss neighborhood as fp32-master lion."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    y = x @ rng.normal(size=(16,)).astype(np.float32)

    def loss_fn(p):
        return jnp.mean((jnp.asarray(x) @ p["w"].astype(jnp.float32) - jnp.asarray(y)) ** 2)

    def train(tx, w0):
        params = {"w": w0}
        state = tx.init(params)
        for _ in range(400):
            grads = {"w": jax.grad(loss_fn)(params)["w"].astype(jnp.float32)}
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return float(loss_fn(params))

    base = train(optax.lion(3e-3, b1=0.9, b2=0.99, weight_decay=0.0),
                 jnp.zeros((16,), jnp.float32))
    sr8 = train(lion_int8_sr(3e-3, b1=0.9, b2=0.99), jnp.zeros((16,), jnp.bfloat16))
    assert sr8 < max(4 * base, 5e-3), (sr8, base)


def test_adamw_sr8_tracks_fp32_adamw():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    y = x @ rng.normal(size=(16,)).astype(np.float32)

    def loss_fn(p):
        return jnp.mean((jnp.asarray(x) @ p["w"].astype(jnp.float32) - jnp.asarray(y)) ** 2)

    def train(tx, w0):
        params = {"w": w0}
        state = tx.init(params)
        for _ in range(400):
            grads = {"w": jax.grad(loss_fn)(params)["w"].astype(jnp.float32)}
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return float(loss_fn(params))

    base = train(optax.adamw(3e-2, weight_decay=0.0), jnp.zeros((16,), jnp.float32))
    sr8 = train(adamw_int8_sr(3e-2), jnp.zeros((16,), jnp.bfloat16))
    assert sr8 < max(4 * base, 5e-3), (sr8, base)


@pytest.mark.slow
def test_sr8_nu_log_sr_tracks_where_nearest_freezes():
    """The log-map SR second-moment EMA reaches its per-lane fixed point g²
    even when per-step increments sit far below one code, while NEAREST
    rounding on the same map stalls at ~3% of it.

    The block scale must be *pinned* to expose the freeze: lane 0 carries
    the block absmax and starts exactly at its own fixed point, so the
    stored fp32 scale never moves.  (While the absmax lane is still
    converging, its fp32-exact motion shifts every other lane's code phase
    each step — an incidental dither that masks the nearest freeze; the
    optimizer inherits that robustness for free, but the mechanism test
    needs it off.)  The other lanes' relative EMA increment
    (1-b2)(g²/v - 1) drops below half a code (~3.3%) at v ≈ g²/34 —
    nearest stops there; SR keeps moving in expectation."""
    n, steps, b2, block = 256, 4000, 0.999, 256
    rng = np.random.default_rng(0)
    g2 = rng.uniform(0.2, 0.3, n).astype(np.float32)
    g2[0] = 1.0                  # lane 0 pins the block scale...
    v0 = np.zeros(n, np.float32)
    v0[0] = 1.0                  # ...and starts at its fixed point
    ent = jnp.asarray(rng.normal(size=(n,)).astype(np.float32))

    def run(salted):
        v = jnp.asarray(v0)
        for t in range(steps):
            v32 = b2 * v + (1 - b2) * jnp.asarray(g2)
            salt = jnp.uint32((t * 2654435761) & 0xFFFFFFFF) if salted else None
            c, s = quantize_u8_log_blockwise(v32, block, salt=salt, entropy=ent)
            v = dequantize_u8_log_blockwise(c, s, block)
        return np.asarray(v)

    target = g2[1:] * (1.0 - b2 ** steps)
    near_ratio = (run(False)[1:] / target).mean()
    sr_ratio = (run(True)[1:] / target).mean()
    # measured: nearest stalls at ~0.031x the fixed point; SR lands at
    # ~1.002x with ~0.05 log2 dispersion across lanes
    assert near_ratio < 0.2, near_ratio
    assert abs(sr_ratio - 1.0) < 0.1, sr_ratio


@pytest.mark.parametrize("make_tx", [lion_int8_sr, adamw_int8_sr])
def test_sr8_apply_updates_reconstructs_bitwise(make_tx):
    """Same optax delta contract as the bf16-SR recipes: the fp32 delta
    through apply_updates lands exactly on the stochastically rounded
    weight (no second rounding)."""
    key = jax.random.key(11)
    p = {"w": jax.random.normal(key, (512,), jnp.float32).astype(jnp.bfloat16)}
    g = {"w": jax.random.normal(jax.random.fold_in(key, 1), (512,), jnp.float32)}
    tx = make_tx(3e-3)
    state = tx.update(g, tx.init(p), p)[1]
    updates, state = tx.update(g, state, p)
    applied = optax.apply_updates(p, updates)
    expect = np.asarray(p["w"], np.float32) + np.asarray(updates["w"], np.float32)
    np.testing.assert_array_equal(
        np.asarray(applied["w"], np.float32),
        expect.astype(jnp.bfloat16).astype(np.float32),
    )
    assert applied["w"].dtype == jnp.bfloat16
    assert state.mu["w"].dtype == jnp.int8
    assert state.mu_scale["w"].dtype == jnp.float32


@pytest.mark.parametrize("make_tx", [lion_int8_sr, adamw_int8_sr])
def test_sr8_update_requires_params(make_tx):
    tx = make_tx()
    state = tx.init({"w": jnp.zeros((4,), jnp.bfloat16)})
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.zeros((4,), jnp.bfloat16)}, state)


def test_sr8_update_is_deterministic():
    """The hashed SR keys derive from (count, leaf, value, grad) only —
    identical inputs give bit-identical codes (the offload==resident and
    bit-exact-resume contract)."""
    rng = np.random.default_rng(5)
    p = {"w": jnp.asarray(rng.normal(size=(256,)).astype(np.float32)).astype(jnp.bfloat16)}
    g = {"w": jnp.asarray(rng.normal(size=(256,)).astype(np.float32))}
    tx = adamw_int8_sr(1e-3)
    u1, s1 = tx.update(g, tx.init(p), p)
    u2, s2 = tx.update(g, tx.init(p), p)
    np.testing.assert_array_equal(np.asarray(s1.mu["w"]), np.asarray(s2.mu["w"]))
    np.testing.assert_array_equal(np.asarray(s1.nu["w"]), np.asarray(s2.nu["w"]))
    np.testing.assert_array_equal(
        np.asarray(u1["w"], np.float32), np.asarray(u2["w"], np.float32))


# ---------------------------------------------------------------------------
# registry + plugin knob + checkpoint round-trip
# ---------------------------------------------------------------------------


def test_make_optimizer_registry():
    from accelerate_tpu.optimizer import OPTIMIZER_RECIPES, make_optimizer, reference_recipe

    assert reference_recipe("lion-sr8") == "lion"
    assert reference_recipe("adamw-sr") == "adamw"
    p = {"w": jnp.zeros((300,), jnp.bfloat16)}
    for name in OPTIMIZER_RECIPES:
        tx = make_optimizer(name)
        tx.init(p)  # constructible + initializable
    # block_size shapes the scale leaves of the -sr8 recipes
    st = make_optimizer("lion-sr8", block_size=64).init(p)
    assert st.mu_scale["w"].shape == (5,)  # ceil(300/64)
    with pytest.raises(ValueError, match="block_size"):
        make_optimizer("lion", block_size=64)
    with pytest.raises(ValueError, match="unknown optimizer recipe"):
        make_optimizer("sgd-sr8")


def test_prepare_optimizer_by_name_reads_plugin_block_size():
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            min_weight_size=0, int8_state_block_size=32),
    )
    opt = acc.prepare_optimizer("adamw-sr8")
    st = opt.init({"w": jnp.zeros((256,), jnp.bfloat16)})
    assert st.mu_scale["w"].shape == (8,)  # 256/32 blocks: the knob landed
    assert st.mu["w"].dtype == jnp.int8 and st.nu["w"].dtype == jnp.uint8


def test_int8_state_block_size_env_default(monkeypatch):
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    assert FullyShardedDataParallelPlugin().int8_state_block_size == 128
    monkeypatch.setenv("ACCELERATE_INT8_STATE_BLOCK", "256")
    assert FullyShardedDataParallelPlugin().int8_state_block_size == 256
    # explicit argument wins over env (the plugin env contract)
    assert FullyShardedDataParallelPlugin(
        int8_state_block_size=64).int8_state_block_size == 64
    with pytest.raises(ValueError, match="int8_state_block_size"):
        FullyShardedDataParallelPlugin(int8_state_block_size=0)


@pytest.mark.parametrize("recipe", ["lion-sr8", "adamw-sr8"])
def test_sr8_state_checkpoint_roundtrip_bit_exact(tmp_path, recipe):
    """save_state/load_state round-trips the int8 codes and fp32 scales
    BIT-exactly (codes are hash-keyed — a lossy round-trip would fork the
    SR stream on resume), and training continues."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        project_dir=str(tmp_path), mixed_precision="bf16",
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0, cpu_offload=True),
    )
    rng = np.random.default_rng(0)
    params = {
        "dense": {"kernel": jnp.asarray(rng.normal(size=(8, 64)).astype(np.float32)) * 0.1,
                  "bias": jnp.zeros((64,))},
        "out": {"kernel": jnp.asarray(rng.normal(size=(64, 1)).astype(np.float32)) * 0.1,
                "bias": jnp.zeros((1,))},
    }
    params = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), params)
    state = acc.create_train_state(params, acc.prepare_optimizer(recipe))

    def loss(p, batch):
        h = jnp.tanh(batch["x"] @ p["dense"]["kernel"] + p["dense"]["bias"])
        pred = (h @ p["out"]["kernel"] + p["out"]["bias"])[..., 0]
        return jnp.mean((pred - batch["y"]) ** 2)

    step = acc.prepare_train_step(loss, max_grad_norm=None)
    batch = {"x": jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32)),
             "y": jnp.asarray(rng.normal(size=(16,)).astype(np.float32))}
    for _ in range(3):
        state, _ = step(state, batch)

    path = acc.save_state(train_state=state)
    zeroed = state.replace(
        params=jax.tree_util.tree_map(jnp.zeros_like, state.params),
        opt_state=jax.tree_util.tree_map(jnp.zeros_like, state.opt_state),
    )
    restored = acc.load_state(path, train_state=zeroed)

    def assert_identical(a, b):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    jax.tree_util.tree_map(assert_identical, restored.opt_state, state.opt_state)
    jax.tree_util.tree_map(assert_identical, restored.params, state.params)
    # int8/uint8 codes really came back as integer dtypes
    assert restored.opt_state.mu["dense"]["kernel"].dtype == jnp.int8
    if recipe == "adamw-sr8":
        assert restored.opt_state.nu["dense"]["kernel"].dtype == jnp.uint8

    # resumed training takes the SAME trajectory as uninterrupted training
    # (deterministic SR keys + bit-exact state)
    cont, _ = step(state, batch)
    res, _ = step(restored, batch)
    jax.tree_util.tree_map(assert_identical, cont.params, res.params)
