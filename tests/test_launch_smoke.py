"""The ONE tier-1 multi-process launch smoke (the rest of the subprocess
self-launch matrix lives in the slow tier, tests/test_launch.py): a minimal
2-process CPU gang over ``jax.distributed`` with a REAL cross-process
collective — pinning the launcher's coordinator wiring and the gloo CPU
collectives backend (state.py enables it before initialize; without it the
CPU backend rejects every multiprocess computation)."""

import os

from accelerate_tpu.test_utils import execute_subprocess, get_launch_command


def _clean_env(**extra):
    env = {
        k: v for k, v in os.environ.items()
        if not k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_"))
    }
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra)
    return env


def test_minimal_two_process_collective_smoke(tmp_path):
    script = tmp_path / "smoke.py"
    script.write_text(
        "import numpy as np\n"
        "from accelerate_tpu import PartialState\n"
        "from accelerate_tpu.ops import operations as ops\n"
        "state = PartialState()\n"
        "assert state.num_processes == 2, state.num_processes\n"
        "summed = np.asarray(ops.reduce(np.ones((3,), np.float32), reduction='sum'))\n"
        "np.testing.assert_allclose(summed, np.full((3,), 2.0, np.float32))\n"
        "state.print('SMOKE OK')\n"
        "state.destroy_process_group()\n"
    )
    cmd = get_launch_command(num_processes=2, num_cpu_devices=1) + [str(script)]
    result = execute_subprocess(cmd, env=_clean_env(), timeout=300)
    assert "SMOKE OK" in result.stdout
