"""Hierarchical ICI→DCN gradient sync (parallel/hierarchical.py): schedule
math vs the flat pmean, the PowerSGD DCN codec with error feedback, the
predicted/measured accounting twins, the Accelerator train-step wiring on a
``dcn × dp_shard`` virtual mesh, and the elastic re-shard restore."""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.parallel.hierarchical import (
    dcn_comm_accounting,
    hierarchical_sync,
    init_dcn_powersgd_state,
    measure_dcn_bytes,
    ring_reduce_factor,
    slab_eligible,
    slab_geometry,
)
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.utils.dataclasses import (
    FullyShardedDataParallelPlugin,
    GradSyncKwargs,
    ProjectConfiguration,
    ShardingStrategy,
)

try:
    from jax import shard_map as _shard_map

    _NO_CHECK = {"check_vma": False}
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _NO_CHECK = {"check_rep": False}


def _fresh():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def _no_shard():
    return FullyShardedDataParallelPlugin(sharding_strategy=ShardingStrategy.NO_SHARD)


def _dcn_mesh(dcn=2, ici=4):
    return Mesh(np.asarray(jax.devices()[: dcn * ici]).reshape(dcn, ici),
                ("dcn", "dp_shard"))


# ---------------------------------------------------------------------------
# slab geometry / schedule math
# ---------------------------------------------------------------------------


def test_slab_geometry_pads_and_near_square():
    g = slab_geometry(16 * 33, 4)
    assert g["chunk"] == 132 and g["padded"] == 528
    assert g["rows"] * g["cols"] >= g["chunk"]
    assert abs(g["rows"] - g["cols"]) <= g["cols"]  # near-square view
    # p=1 degenerates to the whole leaf
    g1 = slab_geometry(100, 1)
    assert g1["chunk"] == g1["padded"] == 100


def test_slab_eligibility_matches_factor_arithmetic():
    big = np.zeros((64, 64), np.float32)
    tiny = np.zeros((4,), np.float32)
    ints = np.zeros((64, 64), np.int32)
    assert slab_eligible(big, 4, rank=2)
    assert not slab_eligible(tiny, 4, rank=2)
    assert not slab_eligible(ints, 4, rank=2)
    assert ring_reduce_factor(1) == 0.0 and ring_reduce_factor(2) == 1.0


def test_hierarchical_dense_equals_flat_pmean():
    mesh = _dcn_mesh()
    rng = np.random.default_rng(0)
    grads = {
        "w": rng.standard_normal((8, 16, 33)).astype(np.float32),
        "b": rng.standard_normal((8, 7)).astype(np.float32),
    }
    spec = P(("dcn", "dp_shard"))

    def flat(gr):
        return jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g[0], ("dcn", "dp_shard")), gr
        )

    def hier(gr):
        local = jax.tree_util.tree_map(lambda g: g[0], gr)
        out, _, _ = hierarchical_sync(local, ("dp_shard",), "dcn")
        return out

    a = _shard_map(flat, mesh=mesh, in_specs=spec, out_specs=P(), **_NO_CHECK)(grads)
    b = _shard_map(hier, mesh=mesh, in_specs=spec, out_specs=P(), **_NO_CHECK)(grads)
    for k in grads:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=1e-5, atol=1e-6)


def test_powersgd_codec_error_feedback_state():
    mesh = _dcn_mesh()
    params = {"w": np.zeros((16, 33), np.float32), "b": np.zeros((7,), np.float32)}
    qs, errs = init_dcn_powersgd_state(params, rank=2, dp_world=8, ici_size=4)
    geo = slab_geometry(16 * 33, 4)
    assert qs["w"].shape == (geo["cols"], 2)
    assert errs["w"].shape == (8, geo["rows"], geo["cols"])
    assert qs["b"] is None and errs["b"] is None  # slab too small to compress

    rng = np.random.default_rng(0)
    grads = {
        "w": rng.standard_normal((8, 16, 33)).astype(np.float32),
        "b": rng.standard_normal((8, 7)).astype(np.float32),
    }
    isl = lambda x: x is None

    def hier_c(gr, qs, errs):
        local = jax.tree_util.tree_map(lambda g: g[0], gr)
        el = jax.tree_util.tree_map(lambda e: e[0], errs)
        out, nq, ne = hierarchical_sync(local, ("dp_shard",), "dcn",
                                        qs=qs, errs=el, rank=2)
        ne = jax.tree_util.tree_map(lambda e: e[None], ne)
        return out, nq, ne

    spec = P(("dcn", "dp_shard"))
    fn = _shard_map(hier_c, mesh=mesh,
                    in_specs=(spec, P(), spec),
                    out_specs=(P(), P(), spec), **_NO_CHECK)
    out, nq, ne = fn(grads, qs, errs)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree_util.tree_leaves(out))
    # error feedback engaged: the residual buffer is non-zero after one step
    assert float(np.abs(np.asarray(ne["w"])).max()) > 0
    # the ineligible leaf took the dense hop: exact world mean
    np.testing.assert_allclose(np.asarray(out["b"]), grads["b"].mean(0),
                               rtol=1e-5, atol=1e-6)


def test_accounting_twins_agree_exactly_and_order():
    """Predicted (dcn_comm_accounting) vs measured (jaxpr walk) per-device
    DCN bytes: EXACT agreement on both the dense and the compressed
    schedule, and compressed < dense < flat."""
    mesh = _dcn_mesh()
    params = {"w": np.zeros((16, 33), np.float32), "b": np.zeros((7,), np.float32)}
    rng = np.random.default_rng(0)
    grads = {
        "w": rng.standard_normal((8, 16, 33)).astype(np.float32),
        "b": rng.standard_normal((8, 7)).astype(np.float32),
    }
    spec = P(("dcn", "dp_shard"))

    def hier(gr):
        local = jax.tree_util.tree_map(lambda g: g[0], gr)
        out, _, _ = hierarchical_sync(local, ("dp_shard",), "dcn")
        return out

    f_dense = _shard_map(hier, mesh=mesh, in_specs=spec, out_specs=P(), **_NO_CHECK)
    measured = measure_dcn_bytes(jax.jit(f_dense).trace(grads).jaxpr, dcn_size=2)
    predicted = dcn_comm_accounting(params, ici_size=4, dcn_size=2)
    assert measured["dcn_bytes"] == predicted["dcn_bytes"]

    qs, errs = init_dcn_powersgd_state(params, rank=2, dp_world=8, ici_size=4)

    def hier_c(gr, qs, errs):
        local = jax.tree_util.tree_map(lambda g: g[0], gr)
        el = jax.tree_util.tree_map(lambda e: e[0], errs)
        out, nq, ne = hierarchical_sync(local, ("dp_shard",), "dcn",
                                        qs=qs, errs=el, rank=2)
        return out, nq, jax.tree_util.tree_map(lambda e: e[None], ne)

    f_c = _shard_map(hier_c, mesh=mesh, in_specs=(spec, P(), spec),
                     out_specs=(P(), P(), spec), **_NO_CHECK)
    measured_c = measure_dcn_bytes(jax.jit(f_c).trace(grads, qs, errs).jaxpr,
                                   dcn_size=2)
    predicted_c = dcn_comm_accounting(params, ici_size=4, dcn_size=2,
                                      compression="powersgd", rank=2)
    assert measured_c["dcn_bytes"] == predicted_c["dcn_bytes"]
    assert measured_c["dcn_bytes"] < measured["dcn_bytes"] < predicted["dcn_bytes_flat"]


def test_accounting_zeros_clean_without_dcn_axis():
    acct = dcn_comm_accounting({"w": np.zeros((64, 64), np.float32)},
                               ici_size=1, dcn_size=1)
    assert acct["dcn_bytes"] == 0 and acct["dcn_bytes_flat"] == 0
    assert acct["dcn_overlap_frac"] == 0.0


# ---------------------------------------------------------------------------
# Accelerator train-step wiring
# ---------------------------------------------------------------------------


def _mlp_init(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": np.asarray(jax.random.normal(k1, (8, 32))) * 0.3,
        "b1": np.zeros((32,), np.float32),
        "w2": np.asarray(jax.random.normal(k2, (32, 1))) * 0.3,
    }


def _mlp_loss(params, batch):
    h = jax.nn.relu(batch["x"] @ params["w1"] + params["b1"])
    return jnp.mean(((h @ params["w2"])[:, 0] - batch["y"]) ** 2)


def _batches(n=4, bs=32, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(8,)).astype(np.float32)
    out = []
    for _ in range(n):
        x = rng.normal(size=(bs, 8)).astype(np.float32)
        out.append({"x": x, "y": x @ w_true})
    return out


def _train(pcfg, handlers=None, plugin=None, steps=12, **acc_kwargs):
    import optax

    _fresh()
    acc = Accelerator(parallelism_config=pcfg, fsdp_plugin=plugin,
                      kwargs_handlers=handlers or [], **acc_kwargs)
    state = acc.create_train_state(_mlp_init(jax.random.key(0)), optax.sgd(0.05))
    step = acc.prepare_train_step(_mlp_loss)
    bs = _batches()
    losses = []
    for i in range(steps):
        state, m = step(state, bs[i % len(bs)])
        losses.append(float(m["loss"]))
    return acc, state, losses


def test_train_step_hierarchical_engages_and_matches_flat():
    acc_h, _, lh = _train(ParallelismConfig(dcn_size=2, dp_shard_size=4),
                          plugin=_no_shard())
    assert acc_h.dcn_sync == {"enabled": True, "dcn_size": 2, "ici_size": 4,
                              "compression": None, "why_not": None}
    acc_f, _, lf = _train(ParallelismConfig(dcn_size=2, dp_shard_size=4),
                          plugin=_no_shard(),
                          handlers=[GradSyncKwargs(hierarchical=False)])
    assert not acc_f.dcn_sync["enabled"]
    np.testing.assert_allclose(lh, lf, rtol=1e-5, atol=1e-6)
    # determinism: the hierarchical trajectory is bitwise-reproducible
    _, _, lh2 = _train(ParallelismConfig(dcn_size=2, dp_shard_size=4),
                       plugin=_no_shard())
    assert lh == lh2


def test_train_step_dcn_powersgd_converges():
    acc, state, losses = _train(
        ParallelismConfig(dcn_size=2, dp_shard_size=4), plugin=_no_shard(),
        handlers=[GradSyncKwargs(dcn_compression="powersgd", rank=2)], steps=60,
    )
    assert acc.dcn_sync["compression"] == "powersgd"
    assert losses[-1] < 0.1, f"dcn-compressed run failed to converge: {losses[-5:]}"
    # comm_state rode the TrainState (error feedback across steps)
    qs, errs = state.comm_state
    assert any(q is not None for q in jax.tree_util.tree_leaves(
        qs, is_leaf=lambda x: x is None))


def test_train_step_traced_dcn_bytes_below_flat_twin():
    """The acceptance pin: the prepared hierarchical step's TRACED program
    moves fewer per-device DCN bytes than the flat-reduce twin, and the
    predicted/measured twins agree (clean-run contract; small slack for the
    loss-scalar psum the predictor ignores)."""
    import optax

    for codec, handler in (
        (None, []),
        ("powersgd", [GradSyncKwargs(dcn_compression="powersgd", rank=2)]),
    ):
        _fresh()
        acc = Accelerator(parallelism_config=ParallelismConfig(dcn_size=2, dp_shard_size=4),
                          fsdp_plugin=_no_shard(), kwargs_handlers=handler)
        params = _mlp_init(jax.random.key(0))
        state = acc.create_train_state(params, optax.sgd(0.05))
        step = acc.prepare_train_step(_mlp_loss)
        b = _batches(1)[0]
        closed = step._jitted.trace(state, b).jaxpr
        measured = measure_dcn_bytes(closed, dcn_size=2)
        predicted = acc.dcn_sync_accounting(params)
        assert predicted["compression"] == codec
        assert measured["dcn_bytes"] < predicted["dcn_bytes_flat"], codec
        # twins agree: the traced step adds only the loss-scalar dcn psum
        # (4 bytes) on top of the predicted gradient traffic
        assert abs(measured["dcn_bytes"] - predicted["dcn_bytes"]) <= 16, (
            codec, measured["dcn_bytes"], predicted["dcn_bytes"],
            [r for r in measured["collectives"]],
        )


def test_incompatible_configs_fall_back_or_raise():
    # auto mode: FULL_SHARD (default for dp_shard>1) falls back to the flat
    # reduction with the blocker recorded
    acc, _, losses = _train(ParallelismConfig(dcn_size=2, dp_shard_size=4))
    assert not acc.dcn_sync["enabled"]
    assert "params sharded" in acc.dcn_sync["why_not"]
    assert all(np.isfinite(losses))
    # hierarchical=True on the same config refuses instead of degrading
    with pytest.raises(ValueError, match="cannot engage"):
        _train(ParallelismConfig(dcn_size=2, dp_shard_size=4),
               handlers=[GradSyncKwargs(hierarchical=True)])
    # the DCN codec cannot ride a mesh without a dcn axis
    with pytest.raises(ValueError, match="dcn_compression"):
        _train(ParallelismConfig(dp_shard_size=8), plugin=_no_shard(),
               handlers=[GradSyncKwargs(dcn_compression="powersgd")])
    # unknown codec name is rejected
    with pytest.raises(ValueError, match="dcn_compression"):
        _train(ParallelismConfig(dcn_size=2, dp_shard_size=4), plugin=_no_shard(),
               handlers=[GradSyncKwargs(dcn_compression="topk")])


def test_flat_powersgd_now_spans_dcn_axis():
    """The DDP-style flat PowerSGD path reduces over the FULL dp plane
    including dcn (``_compression_axes``): a dcn mesh with
    compression='powersgd' still converges, with the factor psums spanning
    both axes."""
    acc, _, losses = _train(
        ParallelismConfig(dcn_size=2, dp_shard_size=4), plugin=_no_shard(),
        handlers=[GradSyncKwargs(compression="powersgd", rank=2)], steps=40,
    )
    assert not acc.dcn_sync["enabled"]  # the flat codec owns the step
    assert losses[-1] < 0.2, losses[-5:]


def test_elastic_reshard_restore_across_chip_counts():
    """Elastic resume, the re-shard half: a checkpoint written on the
    2-slice 8-chip mesh restores BITWISE onto a 4-chip single-slice mesh
    (different process/chip topology), continues training, and the restored
    step counters/stream positions carry over."""
    import optax

    batch = _batches(1)[0]
    with tempfile.TemporaryDirectory() as tmp:
        _fresh()
        acc = Accelerator(
            parallelism_config=ParallelismConfig(dcn_size=2, dp_shard_size=4),
            fsdp_plugin=_no_shard(),
            project_config=ProjectConfiguration(project_dir=tmp,
                                                automatic_checkpoint_naming=True),
        )
        state = acc.create_train_state(_mlp_init(jax.random.key(0)), optax.adam(1e-2))
        step = acc.prepare_train_step(_mlp_loss)
        for _ in range(3):
            state, _m = step(state, batch)
        saved = {k: np.asarray(v) for k, v in state.params.items()}
        acc.save_state(train_state=state)

        _fresh()
        acc2 = Accelerator(
            parallelism_config=ParallelismConfig(
                dp_shard_size=4, devices=tuple(jax.devices()[:4])
            ),
            fsdp_plugin=_no_shard(),
            project_config=ProjectConfiguration(project_dir=tmp,
                                                automatic_checkpoint_naming=True),
        )
        state2 = acc2.create_train_state(_mlp_init(jax.random.key(1)), optax.adam(1e-2))
        restored = acc2.maybe_resume(train_state=state2)
        assert restored is not None and int(restored.step) == 3
        assert acc2.step_count == 3
        for k, v in saved.items():
            np.testing.assert_array_equal(np.asarray(restored.params[k]), v)
        step2 = acc2.prepare_train_step(_mlp_loss)
        restored, m = step2(restored, batch)
        assert np.isfinite(float(m["loss"]))
    _fresh()
