"""The generated API reference stays in sync with the live docstrings
(role of reference docs/source/package_reference autodoc: the docs can't
describe code that no longer exists)."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_api_reference_is_current():
    sys.path.insert(0, str(REPO / "docs"))
    try:
        import gen_api
    finally:
        sys.path.pop(0)
    pages = gen_api.generate()
    api_dir = REPO / "docs" / "api"
    stale = []
    for page, content in pages.items():
        on_disk = api_dir / f"{page}.md"
        if not on_disk.exists() or on_disk.read_text() != content:
            stale.append(page)
    assert not stale, (
        f"docs/api pages out of date: {stale} — run `python docs/gen_api.py`"
    )
    on_disk_pages = {p.stem for p in api_dir.glob("*.md")} - {"index"}
    assert on_disk_pages == set(pages), (
        f"orphaned/missing api pages: {on_disk_pages ^ set(pages)}"
    )


def test_rule_catalog_table_is_current():
    """The rule table in docs/static_analysis.md is generated from
    ``analysis.rules.RULES`` — registering a rule without regenerating
    (the GL110 hand-edit shape from PR 17) must fail here, not drift."""
    sys.path.insert(0, str(REPO / "docs"))
    try:
        import gen_api
    finally:
        sys.path.pop(0)
    on_disk = (REPO / "docs" / "static_analysis.md").read_text()
    assert gen_api.RULE_TABLE_BEGIN in on_disk and gen_api.RULE_TABLE_END in on_disk, (
        "rule-table markers missing from docs/static_analysis.md"
    )
    assert gen_api.inject_rule_table(on_disk) == on_disk, (
        "docs/static_analysis.md rule table out of date — run `python docs/gen_api.py`"
    )
    from accelerate_tpu.analysis.rules import RULES

    for rule_id in RULES:
        assert f"| {rule_id} |" in on_disk, f"{rule_id} missing from the rule table"


# ---------------------------------------------------------------------------
# basic-tutorials tier (VERDICT r4 missing #2): the step-by-step pages must
# stay truthful — code blocks parse, referenced files/subcommands/links exist
# ---------------------------------------------------------------------------

import re

TUTORIALS = ["install.md", "first_launch.md", "notebook.md", "pod.md"]


def _blocks(page, lang):
    text = (REPO / "docs" / "tutorials" / page).read_text()
    return re.findall(rf"```{lang}\n(.*?)```", text, re.DOTALL)


def test_tutorial_pages_exist_and_are_linked():
    for page in TUTORIALS:
        assert (REPO / "docs" / "tutorials" / page).exists(), page
    readme = (REPO / "README.md").read_text()
    assert "tutorials" in readme, "README must point newcomers at docs/tutorials/"


def test_tutorial_python_blocks_compile():
    n = 0
    for page in TUTORIALS:
        for i, block in enumerate(_blocks(page, "python")):
            compile(block, f"{page}[{i}]", "exec")
            n += 1
    assert n >= 4


def test_tutorial_shell_blocks_use_real_subcommands_and_paths():
    import argparse

    from accelerate_tpu.commands.accelerate_cli import build_parser

    sub = next(a for a in build_parser()._actions
               if isinstance(a, argparse._SubParsersAction))
    known = set(sub.choices)
    for page in TUTORIALS:
        for block in _blocks(page, "bash"):
            for m in re.finditer(r"accelerate-tpu\s+([a-z-]+)", block):
                assert m.group(1) in known, f"{page}: unknown subcommand {m.group(1)}"
            for m in re.finditer(r"examples/config_templates/\S+\.yaml", block):
                assert (REPO / m.group(0)).exists(), f"{page}: missing {m.group(0)}"


def test_tutorial_internal_links_resolve():
    for page in TUTORIALS:
        text = (REPO / "docs" / "tutorials" / page).read_text()
        for m in re.finditer(r"\]\(([^)#]+\.md)\)", text):
            target = (REPO / "docs" / "tutorials" / m.group(1)).resolve()
            assert target.exists(), f"{page}: broken link {m.group(1)}"


def test_first_launch_script_actually_trains():
    """The tutorial's train.py is executed verbatim — a beginner's first
    contact must not be broken copy-paste."""
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    block = _blocks("first_launch.md", "python")[0]
    exec(compile(block, "first_launch.md", "exec"), {"__name__": "__tutorial__"})
