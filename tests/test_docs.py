"""The generated API reference stays in sync with the live docstrings
(role of reference docs/source/package_reference autodoc: the docs can't
describe code that no longer exists)."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_api_reference_is_current():
    sys.path.insert(0, str(REPO / "docs"))
    try:
        import gen_api
    finally:
        sys.path.pop(0)
    pages = gen_api.generate()
    api_dir = REPO / "docs" / "api"
    stale = []
    for page, content in pages.items():
        on_disk = api_dir / f"{page}.md"
        if not on_disk.exists() or on_disk.read_text() != content:
            stale.append(page)
    assert not stale, (
        f"docs/api pages out of date: {stale} — run `python docs/gen_api.py`"
    )
    on_disk_pages = {p.stem for p in api_dir.glob("*.md")} - {"index"}
    assert on_disk_pages == set(pages), (
        f"orphaned/missing api pages: {on_disk_pages ^ set(pages)}"
    )
