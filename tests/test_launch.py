"""Subprocess self-launch tests (SURVEY §4 tier-2: a pytest test builds an
``accelerate-tpu launch`` command pointing at the bundled assertion script and
every rank asserts — reference tests/test_multidevice.py:52 pattern)."""

import os

import pytest

from accelerate_tpu.test_utils import execute_subprocess, get_launch_command
from accelerate_tpu.test_utils import test_script_path as _script_path

pytestmark = pytest.mark.slow  # multi-process self-launches, minutes


def _clean_env(**extra):
    env = {k: v for k, v in os.environ.items() if not k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_", "FSDP_"))}
    # Workers force the platform via ACCELERATE_USE_CPU (launch --cpu);
    # drop the pytest XLA_FLAGS so each worker sizes its own device world.
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra)
    return env


def test_single_process_self_launch():
    cmd = get_launch_command(num_processes=1, num_cpu_devices=4) + [str(_script_path())]
    result = execute_subprocess(cmd, env=_clean_env())
    assert "ALL CHECKS PASSED" in result.stdout


def test_two_process_self_launch():
    cmd = get_launch_command(num_processes=2, num_cpu_devices=2) + [str(_script_path())]
    result = execute_subprocess(cmd, env=_clean_env())
    assert "ALL CHECKS PASSED" in result.stdout


def test_four_process_self_launch():
    """4-rank gang (VERDICT r4 #3): interior-source O(1) broadcasts, the
    dispatcher's lookahead broadcast stream, PowerSGD factor psums across
    real processes — the rank-math surfaces a 2-proc gang cannot exercise."""
    cmd = get_launch_command(num_processes=4, num_cpu_devices=1) + [str(_script_path())]
    result = execute_subprocess(cmd, env=_clean_env(), timeout=900)
    assert "ALL CHECKS PASSED" in result.stdout
    assert "dispatcher OK" in result.stdout
    assert "powersgd OK" in result.stdout


def test_four_process_save_kill_resume(tmp_path):
    """save -> worker crash -> gang restart -> resume from the checkpoint,
    all under the real launcher at 4 ranks (VERDICT r4 #3; reference
    elasticity + checkpointing composition)."""
    script = tmp_path / "resume.py"
    script.write_text(
        "import os, pathlib\n"
        "import numpy as np\n"
        "import jax, jax.numpy as jnp, optax\n"
        "from accelerate_tpu import Accelerator\n"
        "from accelerate_tpu.checkpointing import list_checkpoints\n"
        "from accelerate_tpu.utils.dataclasses import ProjectConfiguration\n"
        "work = pathlib.Path(os.environ['WORK_DIR'])\n"
        "sentinel = work / 'crashed_once'\n"
        "acc = Accelerator(project_config=ProjectConfiguration(\n"
        "    project_dir=str(work), automatic_checkpoint_naming=True))\n"
        "def loss_fn(p, b):\n"
        "    return jnp.mean((b['x'] @ p['w'] - b['y']) ** 2)\n"
        "state = acc.create_train_state({'w': jnp.zeros((4,))}, optax.sgd(0.1))\n"
        "step = acc.prepare_train_step(loss_fn)\n"
        "start = 0\n"
        "ckpts = list_checkpoints(str(work))\n"
        "if ckpts:\n"
        "    state = acc.load_state(ckpts[-1], train_state=state)\n"
        "    start = int(state.step)\n"
        "    acc.print(f'RESUMED AT {start}')\n"
        "rng = np.random.default_rng(0)\n"
        "xs = rng.normal(size=(8, 4, 4)).astype(np.float32)\n"
        "w_true = rng.normal(size=(4,)).astype(np.float32)\n"
        "for i in range(start, 8):\n"
        "    b = {'x': xs[i], 'y': xs[i] @ w_true}\n"
        "    state, metrics = step(state, b)\n"
        "    if i == 3:\n"
        "        crash_now = not sentinel.exists() and acc.process_index == 2\n"
        "        if crash_now:\n"
        "            # write BEFORE save_state: its trailing barrier orders the\n"
        "            # sentinel ahead of every rank's post-save progress (other\n"
        "            # ranks free-run — the tiny step has no collectives)\n"
        "            sentinel.write_text('x')\n"
        "        acc.save_state(train_state=state)\n"
        "        if crash_now:\n"
        "            raise SystemExit(9)\n"
        "assert int(state.step) == 8, int(state.step)\n"
        "assert sentinel.exists()\n"
        "acc.print(f'RESUME OK loss={float(metrics[\"loss\"]):.6f}')\n"
    )
    cmd = get_launch_command(num_processes=4, num_cpu_devices=1, max_restarts=1) + [str(script)]
    result = execute_subprocess(
        cmd, env=_clean_env(WORK_DIR=str(tmp_path)), timeout=900
    )
    assert "restarting all 4 workers (attempt 1/1)" in result.stderr
    assert "RESUMED AT 4" in result.stdout
    assert "RESUME OK" in result.stdout


def test_launch_env_reaches_script(tmp_path):
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os\n"
        "assert os.environ['ACCELERATE_MIXED_PRECISION'] == 'bf16'\n"
        "assert os.environ['PARALLELISM_CONFIG_TP_SIZE'] == '2'\n"
        "assert os.environ['ACCELERATE_GRADIENT_ACCUMULATION_STEPS'] == '4'\n"
        "print('ENV OK')\n"
    )
    cmd = get_launch_command(
        num_processes=1, mixed_precision="bf16", tp_size=2, gradient_accumulation_steps=4,
    ) + [str(probe)]
    result = execute_subprocess(cmd, env=_clean_env())
    assert "ENV OK" in result.stdout


def test_debug_launcher_forms_collective_world(tmp_path):
    """debug_launcher forks a 2-process CPU world from a JAX-untouched parent
    (reference launchers.py:276 debug_launcher under gloo)."""
    script = tmp_path / "nb.py"
    script.write_text(
        "def train():\n"
        "    from accelerate_tpu import PartialState\n"
        "    state = PartialState()\n"
        "    assert state.num_processes == 2, state.num_processes\n"
        "    state.print('FORK WORLD OK')\n"
        "\n"
        "from accelerate_tpu.launchers import debug_launcher\n"
        "debug_launcher(train)\n"
    )
    import sys

    result = execute_subprocess([sys.executable, str(script)], env=_clean_env())
    assert "FORK WORLD OK" in result.stdout


def test_launch_propagates_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("raise SystemExit(3)\n")
    cmd = get_launch_command(num_processes=1) + [str(bad)]
    try:
        execute_subprocess(cmd, env=_clean_env())
    except RuntimeError as e:
        assert "code 3" in str(e)
    else:
        raise AssertionError("launch should have propagated the non-zero exit")


def test_launch_max_restarts_recovers_crashed_worker(tmp_path):
    """--max_restarts: worker 1 crashes on the first gang run (sentinel not
    yet present); the launcher restarts the WHOLE gang env-identically and
    the second run succeeds (VERDICT r2 next #9; torchrun-elasticity analog,
    reference commands/launch.py:1023)."""
    sentinel = tmp_path / "crashed_once"
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, pathlib\n"
        f"sentinel = pathlib.Path({str(sentinel)!r})\n"
        "first_run = not sentinel.exists()\n"
        "if first_run and os.environ['ACCELERATE_PROCESS_ID'] == '1':\n"
        "    sentinel.write_text('x')\n"
        "    raise SystemExit(7)\n"
        "from accelerate_tpu import PartialState\n"
        "state = PartialState()\n"
        "assert state.num_processes == 2\n"
        "state.print('RECOVERED OK' if sentinel.exists() else 'NO CRASH?')\n"
    )
    cmd = get_launch_command(num_processes=2, num_cpu_devices=1, max_restarts=1) + [str(script)]
    result = execute_subprocess(cmd, env=_clean_env())
    assert "RECOVERED OK" in result.stdout
    assert "restarting all 2 workers (attempt 1/1)" in result.stderr


def test_launch_max_restarts_exhausted_propagates(tmp_path):
    """A persistently-crashing worker exhausts the restart budget and the
    original exit code still propagates."""
    bad = tmp_path / "bad.py"
    bad.write_text("raise SystemExit(3)\n")
    cmd = get_launch_command(num_processes=2, num_cpu_devices=1, max_restarts=2) + [str(bad)]
    try:
        execute_subprocess(cmd, env=_clean_env())
    except RuntimeError as e:
        assert "code 3" in str(e)
        assert "attempt 2/2" in str(e)
    else:
        raise AssertionError("launch should have propagated the non-zero exit")


def test_launch_child_importable_without_pythonpath(tmp_path):
    """An uninstalled source checkout must stay importable in launched
    workers: the parent resolves the package via cwd (`python -m` from the
    repo root) but the child runs the script by path — the launcher's env
    must carry the package root on PYTHONPATH (regression: `accelerate-tpu
    test` failed with ModuleNotFoundError in the child)."""
    import subprocess
    import sys

    script = tmp_path / "probe.py"
    script.write_text("import accelerate_tpu; print('IMPORT-OK')\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    env.pop("PYTHONPATH", None)  # parent finds the package via cwd only
    result = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "launch", "--cpu", str(script)],
        capture_output=True, text=True, timeout=300, cwd=repo, env=env,
    )
    assert result.returncode == 0, result.stderr
    assert "IMPORT-OK" in result.stdout


def test_two_process_dcn_powersgd_parity(tmp_path):
    """The hierarchical ICI→DCN sync with the PowerSGD DCN codec across a
    REAL 2-process gang: trajectory must be bitwise-identical to the same
    mesh single-process (factor psums + error feedback cross the process
    boundary over the gloo backend)."""
    import json

    from accelerate_tpu.test_utils import launch_parity_script_path

    script = str(launch_parity_script_path())
    env = _clean_env(LAUNCH_LEG_STEPS="4", LAUNCH_LEG_COMPRESS="1")

    def run(nproc, ndev):
        cmd = get_launch_command(num_processes=nproc, num_cpu_devices=ndev) + [script]
        r = execute_subprocess(cmd, env=dict(env), timeout=900)
        return json.loads([l for l in r.stdout.splitlines() if l.startswith("{")][-1])

    one = run(1, 4)
    two = run(2, 2)
    assert one["dcn_sync"]["compression"] == "powersgd"
    assert two["losses"] == one["losses"], (two["losses"], one["losses"])


def test_two_process_rank0_publish_visible_to_peer(tmp_path):
    """Rank-0-only checkpoint publish: save_state on a 2-process gang
    returns on BOTH ranks only after the manifest is visible (non-zero
    ranks wait on it), and each rank then verifies the same checkpoint."""
    script = tmp_path / "publish.py"
    script.write_text(
        "import os, pathlib\n"
        "import numpy as np, jax.numpy as jnp, optax\n"
        "from accelerate_tpu import Accelerator\n"
        "from accelerate_tpu.checkpointing import verify_checkpoint\n"
        "from accelerate_tpu.utils.constants import CHECKPOINT_MANIFEST_NAME\n"
        "from accelerate_tpu.utils.dataclasses import ProjectConfiguration\n"
        "work = os.environ['WORK_DIR']\n"
        "acc = Accelerator(project_config=ProjectConfiguration(\n"
        "    project_dir=work, automatic_checkpoint_naming=True))\n"
        "state = acc.create_train_state({'w': jnp.zeros((4,))}, optax.sgd(0.1))\n"
        "step = acc.prepare_train_step(lambda p, b: jnp.mean((b['x'] @ p['w']) ** 2))\n"
        "state, _ = step(state, {'x': jnp.ones((4, 4))})\n"
        "ckpt = acc.save_state(train_state=state)\n"
        "# EVERY rank sees the complete publish the moment save_state returns\n"
        "assert (pathlib.Path(ckpt) / CHECKPOINT_MANIFEST_NAME).exists(), ckpt\n"
        "ok, problems = verify_checkpoint(ckpt)\n"
        "assert ok, problems\n"
        "print(f'rank {acc.process_index} PUBLISH OK')\n"
        "acc.end_training()\n"
        "from accelerate_tpu import PartialState\n"
        "PartialState().destroy_process_group()\n"
    )
    cmd = get_launch_command(num_processes=2, num_cpu_devices=1) + [str(script)]
    result = execute_subprocess(cmd, env=_clean_env(WORK_DIR=str(tmp_path)), timeout=900)
    assert "PUBLISH OK" in result.stdout
