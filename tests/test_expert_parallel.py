"""Expert parallelism (SURVEY §2.4 P10): routing, dispatch, MoE model,
ep-axis sharding on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.parallel.expert_parallel import (
    RoutingResult,
    expert_capacity,
    expert_parallel_apply,
    get_moe_rules,
    moe_combine,
    moe_dispatch,
    top_k_routing,
)
from accelerate_tpu.parallelism_config import ParallelismConfig


def test_expert_capacity_padding():
    # padded to a multiple of 8, never below 8
    assert expert_capacity(128, 8, 2, 1.25) % 8 == 0
    assert expert_capacity(4, 8, 1, 1.0) == 8
    assert expert_capacity(1024, 8, 2, 1.0) == 256


def test_top_k_routing_shapes_and_weights():
    rng = np.random.default_rng(0)
    s, e, k = 64, 8, 2
    logits = jnp.asarray(rng.normal(size=(s, e)), jnp.float32)
    cap = expert_capacity(s, e, k, 2.0)
    routing = top_k_routing(logits, k, cap)
    assert routing.dispatch.shape == (s, e, cap)
    assert routing.combine.shape == (s, e, cap)
    # with generous capacity every token keeps exactly k dispatched slots
    assert int(jnp.sum(routing.dispatch)) == s * k
    # normalized combine weights sum to 1 per token
    np.testing.assert_allclose(np.sum(routing.combine, axis=(1, 2)), 1.0, atol=1e-5)
    # no expert exceeds capacity
    per_slot = jnp.sum(routing.dispatch, axis=0)  # [E, C]
    assert int(jnp.max(per_slot)) <= 1


def test_routing_drops_beyond_capacity():
    # all tokens want expert 0; capacity 8 → only 8 kept
    s, e = 32, 4
    logits = jnp.tile(jnp.asarray([[10.0, 0.0, 0.0, 0.0]]), (s, 1))
    routing = top_k_routing(logits, 1, 8)
    kept = jnp.sum(routing.dispatch[:, 0, :])
    assert int(kept) == 8
    # dropped tokens have zero combine weight everywhere
    dropped_weight = jnp.sum(routing.combine, axis=(1, 2))
    assert int(jnp.sum(dropped_weight > 1e-6)) == 8


def test_uniform_router_aux_loss_is_one():
    s, e = 1024, 8
    logits = jnp.zeros((s, e))
    routing = top_k_routing(logits, 2, expert_capacity(s, e, 2, 2.0))
    np.testing.assert_allclose(float(routing.aux_loss), 1.0, atol=0.05)


def test_dispatch_combine_roundtrip():
    # top-1, generous capacity: combine(dispatch(x)) with identity experts
    # reproduces x exactly (weights normalize to 1.0 for top-1)
    rng = np.random.default_rng(1)
    s, e, d = 32, 4, 16
    x = jnp.asarray(rng.normal(size=(s, d)), jnp.float32)
    logits = jnp.asarray(rng.normal(size=(s, e)), jnp.float32)
    routing = top_k_routing(logits, 1, expert_capacity(s, e, 1, 4.0))
    grouped = moe_dispatch(x, routing)
    y = moe_combine(grouped, routing)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-5)


@pytest.mark.slow
def test_expert_parallel_apply_matches_local():
    """Explicit shard_map all_to_all path == unsharded local compute."""
    cfg = ParallelismConfig(dp_shard_size=2, ep_size=4)
    mesh = cfg.build_device_mesh()
    rng = np.random.default_rng(2)
    e, c, d = 8, 16, 32
    x = jnp.asarray(rng.normal(size=(e, c, d)), jnp.float32)
    scales = jnp.arange(1.0, e + 1.0)

    def expert_fn(idx, batch):
        return batch * scales[idx][:, None, None]

    expected = x * scales[:, None, None]
    x_sharded = jax.device_put(x, NamedSharding(mesh, P(None, "ep", None)))
    out = expert_parallel_apply(mesh, expert_fn, x_sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-6)


@pytest.mark.slow
def test_expert_parallel_apply_no_ep_axis():
    cfg = ParallelismConfig(dp_shard_size=8)
    mesh = cfg.build_device_mesh()
    x = jnp.ones((4, 8, 16))
    out = expert_parallel_apply(mesh, lambda idx, b: b * 2.0, x)
    np.testing.assert_allclose(np.asarray(out), 2.0)


class TestMixtral:
    def _model(self, **kw):
        from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM

        cfg = MixtralConfig.tiny(dtype=jnp.float32, **kw)
        model = MixtralForCausalLM(cfg)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)), jnp.int32)
        params = model.init(jax.random.key(0), ids)
        return cfg, model, params, ids

    def test_forward_shape(self):
        cfg, model, params, ids = self._model()
        logits = model.apply(params, ids)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_expert_params_stacked(self):
        cfg, model, params, _ = self._model()
        experts = params["params"]["layers_0"]["block_sparse_moe"]["experts"]
        assert experts["gate_proj"].shape == (4, cfg.hidden_size, cfg.intermediate_size)
        assert experts["down_proj"].shape == (4, cfg.intermediate_size, cfg.hidden_size)

    @pytest.mark.slow
    def test_loss_includes_router_aux(self):
        from accelerate_tpu.models import make_mixtral_loss_fn

        cfg, model, params, ids = self._model()
        loss_fn = make_mixtral_loss_fn(model)
        batch = {"input_ids": ids, "labels": ids}
        loss = loss_fn(params, batch)
        assert np.isfinite(float(loss))
        # grads flow to router and experts
        grads = jax.grad(loss_fn)(params, batch)
        g_router = grads["params"]["layers_0"]["block_sparse_moe"]["router"]["kernel"]
        g_expert = grads["params"]["layers_0"]["block_sparse_moe"]["experts"]["gate_proj"]
        assert float(jnp.max(jnp.abs(g_router))) > 0
        assert float(jnp.max(jnp.abs(g_expert))) > 0

    def test_ep_sharded_train_step(self):
        """Full train step with experts sharded over ep=4, dp_shard=2."""
        from accelerate_tpu.models import make_mixtral_loss_fn
        from accelerate_tpu.parallel.sharding import make_sharding_plan, shard_params

        cfg, model, params, ids = self._model()
        pcfg = ParallelismConfig(dp_shard_size=2, ep_size=4)
        mesh = pcfg.build_device_mesh()
        plan = make_sharding_plan(
            params, mesh, pcfg, tp_rules=get_moe_rules(),
        )
        # expert weights actually sharded over ep
        spec = plan["params"]["layers_0"]["block_sparse_moe"]["experts"]["gate_proj"].spec
        assert spec[0] == "ep"
        sharded = shard_params(params, plan)

        loss_fn = make_mixtral_loss_fn(model)
        tx = optax.sgd(1e-2)
        opt_state = tx.init(sharded)

        @jax.jit
        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        batch = {
            "input_ids": jax.device_put(ids, NamedSharding(mesh, P("dp_shard", None))),
            "labels": jax.device_put(ids, NamedSharding(mesh, P("dp_shard", None))),
        }
        params2, opt_state, loss = step(sharded, opt_state, batch)
        assert np.isfinite(float(loss))
        # params changed and kept their sharding
        delta = jax.tree_util.tree_map(lambda a, b: float(jnp.max(jnp.abs(a - b))), params2, sharded)
        assert max(jax.tree_util.tree_leaves(delta)) > 0
