"""graft-lint (accelerate_tpu/analysis): rule-by-rule coverage for both
engines, the planted-bug fixture pack (every planted bug flagged, every
corrected twin quiet), suppression semantics, the repo-wide zero-findings
gate, and the accelerator/CLI surfaces.  All CPU-only: the jaxpr auditor is
a pure abstract trace (``jax.jit(...).trace``) — nothing executes on
device."""

import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from accelerate_tpu.analysis import (
    RULES,
    Finding,
    Report,
    Severity,
    apply_suppressions,
    audit_fn,
    audit_jitted,
    lint_paths,
    lint_source,
    parse_marker,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "analysis_fixtures"


def _load_fixture(name):
    spec = importlib.util.spec_from_file_location(name, FIXTURES / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rules_of(report_or_findings):
    findings = getattr(report_or_findings, "unsuppressed", None)
    findings = findings() if findings else report_or_findings
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# report model + suppression syntax
# ---------------------------------------------------------------------------


def test_severity_ordering_and_parse():
    assert Severity.ERROR > Severity.WARNING > Severity.INFO
    assert Severity.parse("warning") is Severity.WARNING
    assert Severity.parse(Severity.ERROR) is Severity.ERROR


def test_parse_marker_variants():
    rules, reason = parse_marker("x = 1  # graft-lint: disable=GL103 -- intentional host pin")
    assert rules == ("GL103",) and reason == "intentional host pin"
    rules, reason = parse_marker("# graft-lint: disable=GL101, GL104 -- twin hazards")
    assert rules == ("GL101", "GL104") and reason == "twin hazards"
    rules, reason = parse_marker("# graft-lint: disable=GL202")
    assert rules == ("GL202",) and reason is None
    assert parse_marker("# just a comment about graft-lint") is None


def test_suppression_same_line_and_line_above(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text(
        "a = 1  # graft-lint: disable=GL204 -- same-line\n"
        "# graft-lint: disable=GL202 -- line-above\n"
        "b = 2\n"
        "c = 3\n"
    )
    findings = [
        Finding("GL204", Severity.ERROR, "m", path=str(f), line=1),
        Finding("GL202", Severity.ERROR, "m", path=str(f), line=2),
        Finding("GL202", Severity.ERROR, "m", path=str(f), line=3),  # below marker
        Finding("GL202", Severity.ERROR, "m", path=str(f), line=4),  # out of reach
        Finding("GL204", Severity.ERROR, "m", path=str(f), line=3),  # wrong rule
    ]
    out = apply_suppressions(findings)
    assert [x.suppressed for x in out[:5]] == [True, True, True, False, False]
    assert out[0].suppress_reason == "same-line"


def test_suppression_continuation_line_normalizes_to_statement_start(tmp_path):
    """Regression: a jaxpr finding whose source_info points at a
    CONTINUATION line of a multi-line statement must still honor a marker
    anchored on the statement's FIRST line (or the line above it)."""
    f = tmp_path / "mod.py"
    f.write_text(
        "# graft-lint: disable=GL103 -- marker above the statement\n"
        "a = some_call(  # graft-lint: disable=GL104 -- marker on first line\n"
        "    one,\n"
        "    two,\n"
        ")\n"
        "b = other_call(\n"
        "    three,\n"
        ")\n"
    )
    out = apply_suppressions([
        # anchored at continuation lines 3/4 -> normalized to statement
        # start (line 2), where both markers are in reach
        Finding("GL104", Severity.ERROR, "m", path=str(f), line=3),
        Finding("GL103", Severity.ERROR, "m", path=str(f), line=4),
        # the second statement has no marker: normalization must not
        # borrow the first statement's markers
        Finding("GL104", Severity.ERROR, "m", path=str(f), line=7),
    ])
    assert [x.suppressed for x in out] == [True, True, False]
    assert out[0].suppress_reason == "marker on first line"
    assert out[1].suppress_reason == "marker above the statement"


def test_finding_and_report_json_round_trip():
    """to_json -> from_json -> to_json is the identity: same findings,
    same summary, identical re-render (the CI round-trip contract)."""
    rep = Report([
        Finding("GL104", Severity.ERROR, "e", fix_hint="h", path="a.py",
                line=3, engine="jaxpr"),
        Finding("GL402", Severity.WARNING, "w", engine="distributed"),
        Finding("GL103", Severity.WARNING, "s", suppressed=True,
                suppress_reason="why"),
    ])
    back = Report.from_json(rep.to_json())
    assert back.findings == rep.findings
    assert back.to_json() == rep.to_json()
    assert back.render(show_suppressed=True) == rep.render(show_suppressed=True)


def test_bare_suppression_marker_reported_as_gl001(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text("a = 1  # graft-lint: disable=GL204\n")
    out = apply_suppressions(
        [Finding("GL204", Severity.ERROR, "m", path=str(f), line=1)]
    )
    assert out[0].suppressed and out[0].suppress_reason is None
    gl001 = [x for x in out if x.rule == "GL001"]
    assert len(gl001) == 1 and gl001[0].severity == Severity.WARNING


def test_report_counts_exit_code_and_json():
    rep = Report([
        Finding("GL104", Severity.ERROR, "e"),
        Finding("GL102", Severity.WARNING, "w"),
        Finding("GL103", Severity.WARNING, "s", suppressed=True),
    ])
    assert rep.counts() == {"error": 1, "warning": 1, "info": 0, "suppressed": 1}
    assert rep.exit_code(Severity.ERROR) == 1
    assert Report([rep.findings[1]]).exit_code(Severity.ERROR) == 0
    assert Report([rep.findings[1]]).exit_code(Severity.WARNING) == 1
    payload = json.loads(rep.to_json())
    assert payload["summary"]["ok"] is False
    assert {f["rule"] for f in payload["findings"]} == {"GL104", "GL102", "GL103"}


def test_every_emitted_rule_is_in_the_catalog():
    # all three engines draw severities/hints from rules.RULES; ids must resolve
    for rule_id in ("GL001", "GL002", "GL101", "GL102", "GL103", "GL104",
                    "GL105", "GL106", "GL107", "GL108", "GL110", "GL201",
                    "GL202", "GL203", "GL204", "GL205", "GL301", "GL302",
                    "GL303", "GL304", "GL305", "GL306", "GL401", "GL402",
                    "GL403", "GL404"):
        assert rule_id in RULES
        assert RULES[rule_id].summary and RULES[rule_id].fix_hint


# ---------------------------------------------------------------------------
# jaxpr auditor: rule-by-rule over the planted/clean fixture twins
# ---------------------------------------------------------------------------

_JAXPR_CASES = [
    ("wasted_donation_step", "GL101", {"donate_argnums": (0,)}),
    ("key_reuse_step", "GL104", {}),
    ("key_reuse_after_split_step", "GL104", {}),
    ("const_capture_step", "GL102", {}),
    ("transfer_in_trace_step", "GL103", {"default_memory_kind": "device"}),
    ("unsharded_output_step", "GL105", {}),
    ("collective_matmul_hint_step", "GL106", {}),
    ("collective_matmul_rs_hint_step", "GL107", {}),
    ("flat_dcn_reduce_step", "GL108", {}),
    ("unscaled_fp8_dot_step", "GL110", {}),
    ("fused_decode_unscaled_kv_step", "GL110", {}),
    ("fused_verify_unscaled_kv_step", "GL110", {}),
]


@pytest.mark.parametrize("fname,rule,kwargs", _JAXPR_CASES)
def test_jaxpr_planted_bug_is_flagged(fname, rule, kwargs):
    mod = _load_fixture("planted_jaxpr")
    rep = audit_fn(getattr(mod, fname), *mod.example_args()[fname], **kwargs)
    assert rule in _rules_of(rep), rep.render()
    assert all(f.rule in RULES for f in rep.findings)


@pytest.mark.parametrize("fname,rule,kwargs", _JAXPR_CASES)
def test_jaxpr_corrected_twin_is_quiet(fname, rule, kwargs):
    mod = _load_fixture("clean_jaxpr")
    rep = audit_fn(getattr(mod, fname), *mod.example_args()[fname], **kwargs)
    assert not rep.unsuppressed(), rep.render()


def test_jaxpr_audit_accepts_abstract_inputs():
    # ShapeDtypeStruct stand-ins: a 7B-shaped step audits without the memory
    def f(state, batch):
        return state * 0.9 + batch.mean(), (state * batch).sum()

    args = (jax.ShapeDtypeStruct((64, 64), jnp.float32),
            jax.ShapeDtypeStruct((64, 64), jnp.float32))
    assert not audit_fn(f, *args, donate_argnums=(0,)).unsuppressed()

    def wasteful(state, batch):
        return (state * batch).sum()

    assert "GL101" in _rules_of(audit_fn(wasteful, *args, donate_argnums=(0,)))


def test_jaxpr_suppression_resolves_through_source_info(tmp_path):
    # the same inline marker silences a finding discovered from the TRACE
    f = tmp_path / "traced_mod.py"
    f.write_text(
        "import jax\n"
        "def reuse(key, x):\n"
        "    a = jax.random.normal(key, x.shape)\n"
        "    # graft-lint: disable=GL104 -- fixture: correlated streams are the point here\n"
        "    b = jax.random.normal(key, x.shape)\n"
        "    return a + b\n"
    )
    spec = importlib.util.spec_from_file_location("traced_mod", f)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rep = audit_fn(mod.reuse, jax.random.key(0), jnp.ones((4,)))
    assert not rep.unsuppressed(), rep.render()
    assert any(x.rule == "GL104" and x.suppressed for x in rep.findings)


def test_gl107_hint_severity_matches_gl106():
    # GL107 is GL106's row-parallel mirror: same INFO severity, same
    # never-fails-a-run contract
    mod = _load_fixture("planted_jaxpr")
    fname = "collective_matmul_rs_hint_step"
    rep = audit_fn(getattr(mod, fname), *mod.example_args()[fname])
    hints = [f for f in rep.findings if f.rule == "GL107"]
    assert hints and all(f.severity == Severity.INFO for f in hints)
    assert rep.exit_code() == 0


def test_gl108_hint_severity_and_slab_hop_quiet():
    # GL108 is a hint like GL106/107: INFO severity, never fails a run —
    # and a psum over ('dcn',) ALONE (the hierarchical path's own slab hop)
    # must stay quiet even above the size threshold
    mod = _load_fixture("planted_jaxpr")
    fname = "flat_dcn_reduce_step"
    rep = audit_fn(getattr(mod, fname), *mod.example_args()[fname])
    hints = [f for f in rep.findings if f.rule == "GL108"]
    assert hints and all(f.severity == Severity.INFO for f in hints)
    assert rep.exit_code() == 0

    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ("dcn", "dp_shard"))

    def dcn_only(gl):
        return jax.lax.psum(gl[0], ("dcn",))  # the slab hop itself

    fn = _shard_map(dcn_only, mesh=mesh, in_specs=P(("dcn", "dp_shard")),
                    out_specs=P("dp_shard", None), **_no_check)
    rep2 = audit_fn(fn, jax.ShapeDtypeStruct((4, 520, 520), jnp.float32))
    assert not [f for f in rep2.findings if f.rule == "GL108"], rep2.render()


def test_gl106_hint_severity_and_suppressible(tmp_path):
    # GL106 is a *hint*: info severity (never fails a run) and the same
    # source-anchored marker silences it at the all_gather's line
    mod = _load_fixture("planted_jaxpr")
    fname = "collective_matmul_hint_step"
    rep = audit_fn(getattr(mod, fname), *mod.example_args()[fname])
    hints = [f for f in rep.findings if f.rule == "GL106"]
    assert hints and all(f.severity == Severity.INFO for f in hints)
    assert rep.exit_code() == 0  # info never flips the exit code

    f = tmp_path / "ring_candidate.py"
    f.write_text(
        "import jax, numpy as np\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "try:\n"
        "    from jax import shard_map as sm\n"
        "    NC = {'check_vma': False}\n"
        "except ImportError:\n"
        "    from jax.experimental.shard_map import shard_map as sm\n"
        "    NC = {'check_rep': False}\n"
        "def pipe(x, w):\n"
        "    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ('x',))\n"
        "    def body(xl, wl):\n"
        "        # graft-lint: disable=GL106 -- fixture: the monolithic pipe is the point here\n"
        "        full = jax.lax.all_gather(xl, 'x', axis=0, tiled=True)\n"
        "        return jax.lax.dot_general(full, wl, (((1,), (0,)), ((), ())))\n"
        "    return sm(body, mesh=mesh, in_specs=(P('x', None), P(None, None)),\n"
        "              out_specs=P(None, None), **NC)(x, w)\n"
    )
    spec = importlib.util.spec_from_file_location("ring_candidate", f)
    mod2 = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod2)
    rep2 = audit_fn(mod2.pipe, jnp.ones((8, 16)), jnp.ones((16, 4)))
    assert any(x.rule == "GL106" and x.suppressed for x in rep2.findings), rep2.render()
    assert not rep2.unsuppressed(), rep2.render()


def test_audit_jitted_rejects_non_jitted():
    with pytest.raises(TypeError):
        audit_jitted(lambda x: x, jnp.ones(()))


# ---------------------------------------------------------------------------
# AST engine: precise per-rule semantics on inline snippets
# ---------------------------------------------------------------------------


def test_ast_donated_reuse_flags_read_after_donating_call():
    src = (
        "import jax\n"
        "jitted = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"
        "def train(state, batch):\n"
        "    new_state = jitted(state, batch)\n"
        "    return state.sum() + new_state\n"
    )
    findings = lint_source(src, "m.py")
    assert [(f.rule, f.line) for f in findings] == [("GL201", 5)]


def test_ast_donated_reuse_rebinding_is_safe():
    # the canonical loop shape: the result rebinds the donated name
    src = (
        "import jax\n"
        "jitted = jax.jit(lambda s, b: (s, 0.0), donate_argnums=(0,))\n"
        "def train(state, batches):\n"
        "    for b in batches:\n"
        "        state, metrics = jitted(state, b)\n"
        "    return state\n"
    )
    assert lint_source(src, "m.py") == []


def test_ast_donated_reuse_inline_jit_call():
    src = (
        "import jax\n"
        "def f(state, batch):\n"
        "    out = jax.jit(lambda s, b: s, donate_argnums=(0,))(state, batch)\n"
        "    return state, out\n"
    )
    assert "GL201" in _rules_of(lint_source(src, "m.py"))


def test_ast_host_sync_only_inside_jit_contexts():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x).sum()\n"
        "def host_side(x):\n"
        "    return np.asarray(x).sum()\n"  # identical call, no jit: quiet
    )
    findings = lint_source(src, "m.py")
    assert [(f.rule, f.line) for f in findings] == [("GL202", 5)]


def test_ast_jit_context_propagates_through_calls_and_nesting():
    src = (
        "import jax, time\n"
        "def helper(x):\n"
        "    return x.item()\n"          # jitted transitively via step
        "def step(x):\n"
        "    def inner(y):\n"
        "        return time.time() + y\n"  # lexically nested in a context
        "    return helper(x) + inner(x)\n"
        "jitted = jax.jit(step)\n"
    )
    assert _rules_of(lint_source(src, "m.py")) == {"GL202", "GL204"}


def test_ast_float_only_flagged_on_traced_parameters():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def step(x, lr_config):\n"
        "    a = float(x)\n"       # parameter: traced -> flagged
        "    b = float('1e-3')\n"  # literal: quiet
        "    return a + b\n"
    )
    findings = lint_source(src, "m.py")
    assert [(f.rule, f.line) for f in findings] == [("GL202", 4)]


def test_ast_shard_map_compat_fallback_is_allowed():
    good = (
        "try:\n"
        "    from jax import shard_map\n"
        "except ImportError:\n"
        "    from jax.experimental.shard_map import shard_map\n"
    )
    assert lint_source(good, "m.py") == []
    bad = "from jax.experimental.shard_map import shard_map\n"
    assert _rules_of(lint_source(bad, "m.py")) == {"GL203"}


def test_ast_impure_in_jit_variants():
    src = (
        "import time, random\n"
        "import numpy as np\n"
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * time.perf_counter() + random.gauss(0, 1) + np.random.rand()\n"
    )
    findings = [f for f in lint_source(src, "m.py") if f.rule == "GL204"]
    assert len(findings) == 3


def test_ast_donated_reuse_augassign_is_not_a_safe_rebinding():
    # `state += 1` READS the donated buffer before writing it — the Store
    # ctx on the AugAssign target must not retire the hazard
    src = (
        "import jax\n"
        "jitted = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"
        "def train(state, batch):\n"
        "    out = jitted(state, batch)\n"
        "    state += 1\n"
        "    return out\n"
    )
    findings = lint_source(src, "m.py")
    assert [(f.rule, f.line) for f in findings] == [("GL201", 5)]


def test_ast_empty_donate_argnums_donates_nothing():
    # explicit `donate_argnums=()` is fully literal: no GL201 false positive
    src = (
        "import jax\n"
        "jitted = jax.jit(lambda s, b: s, donate_argnums=())\n"
        "def train(state, batch):\n"
        "    out = jitted(state, batch)\n"
        "    return state, out\n"
    )
    assert lint_source(src, "m.py") == []


def test_stale_bare_marker_is_reported_and_not_doubled(tmp_path):
    # a bare marker matching NO finding still violates the GL001 contract
    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # graft-lint: disable=GL204\n")
    rep = lint_paths([stale])
    assert [(f.rule, f.line) for f in rep.unsuppressed()] == [("GL001", 1)]
    # and when a bare marker DOES suppress something, GL001 appears once
    both = tmp_path / "both.py"
    both.write_text(
        "import jax, time\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * time.time()  # graft-lint: disable=GL204\n"
    )
    rep2 = lint_paths([both])
    gl001 = [f for f in rep2.unsuppressed() if f.rule == "GL001"]
    assert len(gl001) == 1 and gl001[0].line == 4
    assert any(f.rule == "GL204" and f.suppressed for f in rep2.findings)


def test_ast_syntax_error_is_reported_as_engine_error():
    findings = lint_source("def f(:\n", "broken.py")
    assert findings and findings[0].rule == "GL002"
    assert findings[0].severity == Severity.ERROR


def test_lint_paths_reports_missing_explicit_target(tmp_path):
    # a typo'd CI path must fail the run, never report clean
    rep = lint_paths([tmp_path / "no_such_file.py"])
    assert _rules_of(rep) == {"GL002"}
    assert rep.exit_code(Severity.ERROR) == 1


def test_directory_sweeps_prune_vendored_dirs(tmp_path):
    (tmp_path / ".venv" / "lib").mkdir(parents=True)
    (tmp_path / ".venv" / "lib" / "vendored.py").write_text(
        "from jax.experimental.shard_map import shard_map\n"
    )
    (tmp_path / "mine.py").write_text(
        "from jax.experimental.shard_map import shard_map\n"
    )
    rep = lint_paths([tmp_path])
    assert [Path(f.path).name for f in rep.unsuppressed()] == ["mine.py"]


# ---------------------------------------------------------------------------
# the fixture pack: planted bugs flagged, corrected twins quiet
# ---------------------------------------------------------------------------


def test_fixture_donate_race_planted_vs_fixed():
    planted = lint_paths([FIXTURES / "planted_donate_race.py"], excludes=())
    assert _rules_of(planted) == {"GL201"}, planted.render()
    fixed = lint_paths([FIXTURES / "fixed_donate_race.py"], excludes=())
    assert not fixed.unsuppressed(), fixed.render()


def test_fixture_snapshot_race_planted_vs_clean():
    """GL206: donating a name an async_save=True initiator still holds is
    flagged; draining (wait_for_checkpoint) or rebinding first is quiet."""
    planted = lint_paths([FIXTURES / "planted_snapshot_race.py"], excludes=())
    assert _rules_of(planted) == {"GL206"}, planted.render()
    clean = lint_paths([FIXTURES / "clean_snapshot_race.py"], excludes=())
    assert not clean.unsuppressed(), clean.render()


def test_fixture_ast_planted_all_rules_fire():
    rep = lint_paths([FIXTURES / "planted_ast_rules.py"], excludes=())
    assert _rules_of(rep) == {"GL202", "GL203", "GL204"}, rep.render()
    # every planted host-sync variant is individually caught
    gl202 = [f for f in rep.unsuppressed() if f.rule == "GL202"]
    assert len(gl202) == 4  # .item / np.asarray / float(param) / .tolist


def test_fixture_ast_clean_twins_quiet():
    rep = lint_paths([FIXTURES / "clean_ast_rules.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixture_resilience_planted_gl205_fires():
    rep = lint_paths([FIXTURES / "planted_resilience.py"], excludes=())
    assert _rules_of(rep) == {"GL205"}, rep.render()
    findings = [f for f in rep.unsuppressed() if f.rule == "GL205"]
    # 3 non-atomic write variants (open-wb, json.dump, pickle.dump) + 1
    # swallowed-exception variant, each individually located
    assert len(findings) == 4, rep.render()
    assert sum("atomic publish" in f.message for f in findings) == 3
    assert sum("except Exception: pass" in f.message for f in findings) == 1


def test_fixture_resilience_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_resilience.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixture_serving_planted_gl201_fires():
    """The serving-decode donated-cache reuse (the paged-pool flavor of the
    PR 2 async-ckpt race) is flagged at the AST level."""
    rep = lint_paths([FIXTURES / "planted_serving.py"], excludes=())
    assert "GL201" in _rules_of(rep), rep.render()


def test_fixture_serving_planted_gl101_wasted_pool_donation():
    """A serving step that donates the cache but returns only logits wastes
    the donation — the jaxpr auditor flags it, and the corrected twin
    (updated pool returned) is quiet."""
    planted = _load_fixture("planted_serving")
    args = planted.example_args()["decode_step_drops_pool"]
    rep = audit_fn(planted.decode_step_drops_pool, *args, donate_argnums=(0,))
    assert "GL101" in _rules_of(rep), rep.render()

    clean = _load_fixture("clean_serving")
    args = clean.example_args()["decode_step_drops_pool"]
    rep = audit_fn(clean.decode_step_drops_pool, *args, donate_argnums=(0,))
    assert not rep.unsuppressed(), rep.render()


def test_fixture_serving_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_serving.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixture_lora_planted_gl305_adapter_count_trace():
    """A program keyed on the adapter-stack width re-specializes per tenant
    census — the AST recompile rule flags it; the clean twin (static pool
    width, id routing) stays quiet."""
    rep = lint_paths([FIXTURES / "planted_lora.py"], excludes=())
    assert "GL305" in _rules_of(rep), rep.render()


def test_fixture_lora_planted_gl101_dropped_pool_donation():
    """An adapter-pool insert that donates the stacks but returns only a
    scalar wastes the donation (the hot-swap analog of the dropped-KV-pool
    shape) — the jaxpr auditor flags it; the corrected twin (updated pool
    returned) is quiet."""
    planted = _load_fixture("planted_lora")
    args = planted.example_args()["insert_drops_pool"]
    rep = audit_fn(planted.insert_drops_pool, *args, donate_argnums=(0,))
    assert "GL101" in _rules_of(rep), rep.render()

    clean = _load_fixture("clean_lora")
    args = clean.example_args()["insert_drops_pool"]
    rep = audit_fn(clean.insert_drops_pool, *args, donate_argnums=(0,))
    assert not rep.unsuppressed(), rep.render()


def test_fixture_lora_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_lora.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixture_speculate_planted_gl201_draft_verify_boundary():
    """The drafting layer reading the donated cache after the verify
    dispatch (the draft/verify boundary race) is flagged at the AST
    level."""
    rep = lint_paths([FIXTURES / "planted_speculate.py"], excludes=())
    assert "GL201" in _rules_of(rep), rep.render()


def test_fixture_speculate_planted_gl305_k_dependent_trace():
    """A verify program keyed on the drafts' width re-specializes per draft
    depth — the AST recompile rule flags it; the clean twin (static bucket
    from the fixed ladder) stays quiet."""
    rep = lint_paths([FIXTURES / "planted_speculate.py"], excludes=())
    assert "GL305" in _rules_of(rep), rep.render()


def test_fixture_speculate_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_speculate.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixture_overload_planted_gl201_cancel_release_boundary():
    """The cancel path's reclaim accounting reading the donated cache after
    the release dispatch (the async-ckpt race across the cancel/release
    boundary) is flagged at the AST level."""
    rep = lint_paths([FIXTURES / "planted_overload.py"], excludes=())
    assert "GL201" in _rules_of(rep), rep.render()


def test_fixture_overload_planted_gl305_queue_length_trace():
    """A shed program keyed on the waiting line's live length re-specializes
    per queue depth — the AST recompile rule flags it; the clean twin
    (static ``max_queue`` bound) stays quiet."""
    rep = lint_paths([FIXTURES / "planted_overload.py"], excludes=())
    assert "GL305" in _rules_of(rep), rep.render()


def test_fixture_overload_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_overload.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixture_prefix_planted_gl201_share_boundary():
    """Reading the donated block table back AFTER the adopt dispatch to
    build the COW release keep counts (the async-ckpt race applied across
    the share boundary) is flagged at the AST level."""
    rep = lint_paths([FIXTURES / "planted_prefix.py"], excludes=())
    assert "GL201" in _rules_of(rep), rep.render()


def test_fixture_prefix_planted_gl305_hit_length_trace():
    """An adopt program keyed on this admission's matched-prefix length
    re-specializes per hit depth — the AST recompile rule flags it; the
    clean twin (static pages_per_slot bound, hit length as a masked
    argument) stays quiet."""
    rep = lint_paths([FIXTURES / "planted_prefix.py"], excludes=())
    assert "GL305" in _rules_of(rep), rep.render()


def test_fixture_prefix_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_prefix.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_gl205_one_hop_name_resolution_and_scope():
    # the live path reaches the write through a local assignment — still hit
    src = (
        "import os, pickle\n"
        "def save(step, tree):\n"
        "    d = 'runs/checkpoint_%d' % step\n"
        "    with open(d + '/w.bin', 'wb') as f:\n"
        "        f.write(tree)\n"
    )
    assert {f.rule for f in lint_source(src, "m.py")} == {"GL205"}
    # the tmp-stage + os.replace idiom retires it
    fixed = (
        "import os, pickle\n"
        "def save(step, tree):\n"
        "    d = 'runs/checkpoint_%d.tmp' % step\n"
        "    with open(d + '/w.bin', 'wb') as f:\n"
        "        f.write(tree)\n"
        "    os.replace(d, d[:-4])\n"
    )
    assert lint_source(fixed, "m.py") == []
    # a 2-argument str.replace path-mangle is NOT an atomic publish — only
    # the 1-argument Path.replace/rename form (or os.replace & co.) retires
    # the hazard
    str_replace = (
        "def save(step, data):\n"
        "    d = ('ckpts/checkpoint_%d' % step).replace('//', '/')\n"
        "    with open(d + '/w.bin', 'wb') as f:\n"
        "        f.write(data)\n"
    )
    assert {f.rule for f in lint_source(str_replace, "m.py")} == {"GL205"}
    # except-pass only fires on the resilience/checkpoint spine paths
    swallow = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    assert lint_source(swallow, "some/module.py") == []
    assert {f.rule for f in lint_source(swallow, "pkg/checkpoint_utils.py")} == {"GL205"}


def test_fixture_telemetry_planted_gl109_fires():
    """Every planted timing-without-block shape is individually caught: the
    decorated jit, the `name = jax.jit(...)` binding, the inline
    `jax.jit(f)(x)` call, and the materialize-before-the-LAST-dispatch
    variant (the float() covers only the first call)."""
    rep = lint_paths([FIXTURES / "planted_telemetry.py"], excludes=())
    assert _rules_of(rep) == {"GL109"}, rep.render()
    hits = [f for f in rep.unsuppressed() if f.rule == "GL109"]
    assert len(hits) == 4, rep.render()
    # INFO hint: flags the delta line, never fails a run
    assert all(f.severity == Severity.INFO for f in hits)
    assert rep.exit_code() == 0


def test_fixture_telemetry_clean_twin_quiet():
    """The corrected twins (block_until_ready / float fetch / np.asarray
    before the closing clock read, plain host timing, jit outside the
    window) stay quiet — the bench.py timed-loop idiom passes clean."""
    rep = lint_paths([FIXTURES / "clean_telemetry.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_gl109_suppressible_with_rationale(tmp_path):
    f = tmp_path / "timed.py"
    f.write_text(
        "import time\n"
        "import jax\n"
        "f = jax.jit(lambda x: x)\n"
        "def g(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f(x)\n"
        "    # graft-lint: disable=GL109 -- fixture: dispatch latency is what this micro-bench measures\n"
        "    dt = time.perf_counter() - t0\n"
        "    return y, dt\n"
    )
    rep = lint_paths([f])
    assert not rep.unsuppressed(), rep.render()
    assert any(x.rule == "GL109" and x.suppressed for x in rep.findings)


def test_fixture_distributed_planted_gl401_schedule_divergence():
    """Two roles whose traced collective schedules reverse the rendezvous
    order: the comparator flags the first diverging index — the deadlock a
    launched gang would hit, caught before any process spawns."""
    from accelerate_tpu.analysis import audit_collective_schedules

    mod = _load_fixture("planted_distributed")
    findings = audit_collective_schedules(mod.gl401_schedules())
    assert _rules_of(findings) == {"GL401"}, findings
    assert "rendezvous 0" in findings[0].message
    assert findings[0].severity == Severity.ERROR


def test_fixture_distributed_planted_gl402_double_pin():
    """A ≥1 MiB activation pinned to one sharding and re-pinned to another:
    the predicted GSPMD reshard is flagged with its byte cost."""
    from accelerate_tpu.analysis import audit_resharding

    mod = _load_fixture("planted_distributed")
    (x,) = mod.example_args()["gl402_double_pin_step"]
    findings = audit_resharding(jax.jit(mod.gl402_double_pin_step).trace(x))
    assert _rules_of(findings) == {"GL402"}, findings
    assert "MiB" in findings[0].message


def test_fixture_distributed_planted_gl403_schema_mismatch():
    """int8-quantized prefill vs dense-bf16 decode: the schemas disagree on
    dtype, payload leaves, and bytes/page — the gate flags it AND the
    runtime (check_wire_schemas, the PagedKVTransport constructor's check)
    raises with the pinned historical phrasing."""
    from accelerate_tpu.analysis import audit_wire_schema, check_wire_schemas

    mod = _load_fixture("planted_distributed")
    src, dst = mod.gl403_schemas()
    findings = audit_wire_schema(src, dst)
    assert _rules_of(findings) == {"GL403"}, findings
    assert "kv_dtype" in findings[0].message
    with pytest.raises(ValueError, match="KV page dtypes must match"):
        check_wire_schemas(src, dst)


def test_fixture_distributed_planted_gl404_warmup_gap():
    """The decode role warms only the decode program but can be dispatched
    release + wire_recv — the statically-proven strict_compiles violation."""
    from accelerate_tpu.analysis import audit_warmup_coverage

    mod = _load_fixture("planted_distributed")
    findings = audit_warmup_coverage(*mod.gl404_coverage())
    assert _rules_of(findings) == {"GL404"}, findings
    assert "release" in findings[0].message and "wire_recv" in findings[0].message


def test_fixture_distributed_clean_twins_quiet():
    """Every corrected GL4xx twin is quiet: matched schedules, idempotent
    pins, identical schemas (check_wire_schemas passes), covering warmup."""
    from accelerate_tpu.analysis import (
        audit_collective_schedules,
        audit_resharding,
        audit_warmup_coverage,
        audit_wire_schema,
        check_wire_schemas,
    )

    mod = _load_fixture("clean_distributed")
    assert audit_collective_schedules(mod.gl401_schedules()) == []
    (x,) = mod.example_args()["gl402_double_pin_step"]
    assert audit_resharding(jax.jit(mod.gl402_double_pin_step).trace(x)) == []
    src, dst = mod.gl403_schemas()
    assert audit_wire_schema(src, dst) == []
    check_wire_schemas(src, dst)  # must not raise
    assert audit_warmup_coverage(*mod.gl404_coverage()) == []


def test_pair_preflight_matched_pair_clean_and_planted_mismatch_fires():
    """The full pair gate: a matched prefill/decode pair audits clean
    (schema_ok, symmetric wire legs, covered warmup on both roles); the
    same pair with a planted kv_dtype skew fires GL403.  Trace-only —
    nothing compiles."""
    from accelerate_tpu.analysis import pair_preflight
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    cfg = LlamaConfig.tiny()
    plugin = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                           num_pages=40, prefill_chunk=32,
                           prefill_buckets=(16, 32), decode_kernel="native")
    findings, summary = pair_preflight(cfg, plugin, plugin)
    assert findings == [], findings
    assert summary["schema_ok"] and summary["wire_legs"]
    for role in ("prefill", "decode"):
        r = summary["roles"][role]
        assert set(r["dispatchable"]) <= set(r["warmed"]), r

    import dataclasses
    planted = dataclasses.replace(plugin, kv_dtype="fp8")
    findings, summary = pair_preflight(cfg, planted, plugin, trace_wire=False)
    assert "GL403" in _rules_of(findings), findings
    assert summary["schema_ok"] is False


def test_fixture_fleet_planted_router_pair_fires_gl401_and_gl403():
    """The fleet-router go-live gate: a role-mismatched replica pair
    (int8 prefill vs dense decode) routed through ``pair_preflight`` fires
    BOTH GL403 (schemas disagree) and GL401 (the handoff wire-leg
    schedules diverge — the scale legs exist on one side only).
    Trace-only — nothing compiles."""
    from accelerate_tpu.analysis import pair_preflight

    mod = _load_fixture("planted_fleet")
    findings, summary = pair_preflight(*mod.router_pair())
    rules = _rules_of(findings)
    assert {"GL401", "GL403"} <= rules, findings
    assert summary["schema_ok"] is False


def test_fixture_fleet_clean_router_pair_quiet():
    """The corrected twin: matched int8 wire schemas with per-role
    geometry freedom (slots/pages/chunk/buckets/speculation differ across
    the split) audits clean through the FULL gate, traced wire programs
    included."""
    from accelerate_tpu.analysis import pair_preflight

    mod = _load_fixture("clean_fleet")
    findings, summary = pair_preflight(*mod.router_pair())
    assert findings == [], findings
    assert summary["schema_ok"] and summary["wire_legs"]


def test_every_rule_has_planted_and_clean_fixture_twins():
    """The fixture meta-gate: every registered GLxxx rule id appears in at
    least one planted-fires fixture AND at least one clean-quiet twin under
    ``tests/analysis_fixtures/`` — a future rule can't land untested."""
    import re

    planted, clean = set(), set()
    for p in FIXTURES.glob("*.py"):
        ids = set(re.findall(r"\bGL\d{3}\b", p.read_text()))
        if p.name.startswith("planted_"):
            planted |= ids
        elif p.name.startswith(("clean_", "fixed_")):
            clean |= ids
    for rule_id in RULES:
        assert rule_id in planted, f"{rule_id} has no planted-fires fixture"
        assert rule_id in clean, f"{rule_id} has no clean-quiet fixture twin"


def test_fixture_meta_planted_gl001_and_gl002_fire():
    """The engine-discipline twins: a bare (rationale-less) marker that DOES
    suppress a finding fires GL001; an unparseable target fires GL002."""
    rep = lint_paths([FIXTURES / "planted_meta.py"], excludes=())
    assert _rules_of(rep) == {"GL001"}, rep.render()
    assert any(f.rule == "GL204" and f.suppressed for f in rep.findings)
    rep2 = lint_paths([FIXTURES / "planted_engine_error.py"], excludes=())
    assert _rules_of(rep2) == {"GL002"}, rep2.render()


def test_fixture_meta_clean_twin_quiet():
    rep = lint_paths([FIXTURES / "clean_meta.py"], excludes=())
    assert not rep.unsuppressed(), rep.render()


def test_fixtures_are_excluded_from_repo_sweeps_by_default():
    rep = lint_paths([FIXTURES])
    assert rep.findings == []


# ---------------------------------------------------------------------------
# the repo gate + the real hot spots
# ---------------------------------------------------------------------------


def test_repo_is_lint_clean():
    """The acceptance gate: zero unsuppressed findings over the whole tree
    (fixtures excluded — they are the planted bugs)."""
    rep = lint_paths([REPO])
    assert not rep.unsuppressed(), rep.render()


def test_canonical_train_step_audits_clean():
    # hot spot 1: the real prepare_train_step donation/pinning/RNG plumbing
    from accelerate_tpu.commands.lint import audit_canonical_step

    for optimizer in ("lion", "adamw-sr8"):
        rep = audit_canonical_step(optimizer)
        assert not rep.unsuppressed(), f"{optimizer}:\n{rep.render()}"
        from accelerate_tpu.state import AcceleratorState, GradientState
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()


def test_offloaded_pipelined_step_audits_clean_tpu_shaped():
    """Hot spot 2 (ops/streaming.py pipeline inside the offloaded step),
    audited as if on TPU (default_memory_kind='device'): every in-trace
    transfer must be an inline-suppressed intentional pipeline stage."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    plugin = FullyShardedDataParallelPlugin(
        cpu_offload=True, host_update_chunk_gib=1e-6, host_update_pipeline=True
    )
    acc = Accelerator(fsdp_plugin=plugin)
    params = {"w": jnp.zeros((16, 16)), "b": jnp.zeros((16,))}

    def loss_fn(p, batch):
        return jnp.mean((batch @ p["w"] + p["b"]) ** 2)

    state = acc.create_train_state(params, "lion-sr")
    step = acc.prepare_train_step(loss_fn)
    rep = audit_jitted(step, state, jax.ShapeDtypeStruct((8, 16), jnp.float32),
                       default_memory_kind="device")
    assert not rep.unsuppressed(), rep.render()
    suppressed = [f for f in rep.findings if f.suppressed]
    assert suppressed, "expected the intentional pipeline transfers to be visible-but-suppressed"
    assert all(f.suppress_reason for f in suppressed)


def test_async_snapshot_copy_audits_clean():
    # hot spot 3: the PR 2 fix's snapshot primitive itself
    from accelerate_tpu.checkpointing import _sharded_copy_fn
    from accelerate_tpu.analysis import audit_traced

    arr = jnp.ones((8, 8))
    tr = _sharded_copy_fn(arr.sharding).trace(arr)
    rep = audit_traced(tr, default_memory_kind="device")
    assert not rep.unsuppressed(), rep.render()


# ---------------------------------------------------------------------------
# accelerator + CLI surfaces
# ---------------------------------------------------------------------------


def test_accelerator_audit_step_returns_report():
    from accelerate_tpu import Accelerator

    acc = Accelerator()
    params = {"w": jnp.zeros((4, 4))}

    def loss_fn(p, batch):
        return jnp.mean((batch @ p["w"]) ** 2)

    state = acc.create_train_state(params, "lion")
    step = acc.prepare_train_step(loss_fn)
    rep = acc.audit_step(step, state, jax.ShapeDtypeStruct((2, 4), jnp.float32),
                         log=False)
    assert isinstance(rep, Report) and not rep.unsuppressed()
    # default: audits the last prepared step
    rep2 = acc.audit_step(None, state, jax.ShapeDtypeStruct((2, 4), jnp.float32),
                          log=False)
    assert not rep2.unsuppressed()


def test_accelerate_lint_env_hook_audits_at_first_step(monkeypatch):
    from accelerate_tpu import Accelerator

    monkeypatch.setenv("ACCELERATE_LINT", "1")
    acc = Accelerator()
    params = {"w": jnp.zeros((4, 4))}

    def loss_fn(p, batch):
        return jnp.mean((batch @ p["w"]) ** 2)

    state = acc.create_train_state(params, "lion")
    step = acc.prepare_train_step(loss_fn)
    assert step._lint_report is None
    state, _ = step(state, jnp.ones((2, 4)))
    assert step._lint_report is not None
    assert step._lint_report.summary()["ok"] is True
    # the step still trains (the audit is trace-only)
    state, metrics = step(state, jnp.ones((2, 4)))
    assert jnp.isfinite(metrics["loss"])


def test_lint_cli_end_to_end():
    """The acceptance command: ``python -m accelerate_tpu lint`` exits 0 on
    the repo (AST sweep + canonical step audit)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu", "lint", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    payload = json.loads(out.stdout)
    assert payload["summary"]["ok"] is True
    assert payload["summary"]["error"] == payload["summary"]["warning"] == 0


def test_lint_cli_fails_on_planted_bugs():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu", "lint", "--no-step-audit",
         str(FIXTURES / "planted_donate_race.py")],
        cwd=REPO, capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 1
    assert "GL201" in out.stdout
