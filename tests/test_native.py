"""Native runtime tests: parallel IO engine, safetensors serializer, staging
ring, and the ring-backed dataloader prefetch path.

The reference has no in-tree native layer (SURVEY §2 language note) — its
equivalents live in torch DataLoader workers / safetensors' Rust core, tested
indirectly.  Here the native runtime is in-tree, so it gets direct coverage,
including cross-validation of the safetensors format against the safetensors
library in both directions.
"""

import threading
import zlib

import jax
import numpy as np
import pytest

from accelerate_tpu import native
from accelerate_tpu.utils import serialization as S

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native runtime not built (no C++ toolchain)"
)


# ---------------------------------------------------------------------------
# IO engine
# ---------------------------------------------------------------------------


def test_write_read_roundtrip(tmp_path):
    data = np.random.default_rng(0).integers(0, 255, 3_000_000, dtype=np.uint8)
    path = tmp_path / "blob.bin"
    native.write_file(path, data, nthreads=4)
    assert native.file_size(path) == data.nbytes
    back = native.read_file(path, nthreads=4)
    assert np.array_equal(data, back)


def test_read_offset_and_out_buffer(tmp_path):
    data = np.arange(1000, dtype=np.uint8)
    path = tmp_path / "blob.bin"
    native.write_file(path, data)
    out = np.empty(100, np.uint8)
    got = native.read_file(path, nbytes=100, offset=50, out=out)
    assert got is out
    assert np.array_equal(out, data[50:150])


def test_segments_scatter_gather(tmp_path):
    path = tmp_path / "seg.bin"
    a = np.random.rand(64, 3).astype(np.float32)
    b = np.arange(17, dtype=np.int64)
    native.write_file_segments(path, [(0, a), (1024, b)])
    out_a, out_b = np.empty_like(a), np.empty_like(b)
    native.read_file_segments(path, [(0, out_a), (1024, out_b)])
    assert np.array_equal(a, out_a) and np.array_equal(b, out_b)


def test_crc32_matches_zlib():
    data = np.random.default_rng(1).integers(0, 255, 100_000, dtype=np.uint8)
    assert native.crc32(data) == zlib.crc32(data.tobytes())


def test_missing_file_raises(tmp_path):
    with pytest.raises(OSError):
        native.read_file(tmp_path / "nope.bin", nbytes=10)
    with pytest.raises(OSError):
        native.file_size(tmp_path / "nope.bin")


# ---------------------------------------------------------------------------
# safetensors serializer (cross-validated against the safetensors library)
# ---------------------------------------------------------------------------


def _sample_tensors():
    import ml_dtypes

    rng = np.random.default_rng(2)
    return {
        "layer/kernel": rng.standard_normal((32, 16)).astype(np.float32),
        "layer/bias": rng.standard_normal(16).astype(np.float16),
        "ids": np.arange(7, dtype=np.int64),
        "bf16": rng.standard_normal((8, 8)).astype(ml_dtypes.bfloat16),
        "empty": np.zeros((0, 4), np.float32),
        "scalarish": np.array([3], np.int32),
    }


def test_safetensors_lib_reads_native_file(tmp_path):
    from safetensors.numpy import load_file

    tensors = _sample_tensors()
    path = str(tmp_path / "m.safetensors")
    S.save_safetensors(path, tensors, metadata={"format": "np"})
    back = load_file(path)
    assert set(back) == set(tensors)
    for k, v in tensors.items():
        assert np.array_equal(back[k].view(np.uint8), np.asarray(v).view(np.uint8)), k


def test_native_reads_safetensors_lib_file(tmp_path):
    from safetensors.numpy import save_file

    tensors = _sample_tensors()
    path = str(tmp_path / "m.safetensors")
    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()}, path)
    back = S.load_safetensors(path)
    assert set(back) == set(tensors)
    for k, v in tensors.items():
        assert back[k].dtype == np.asarray(v).dtype
        assert np.array_equal(back[k].view(np.uint8), np.asarray(v).view(np.uint8)), k


def test_lazy_file_and_name_subset(tmp_path):
    tensors = _sample_tensors()
    path = str(tmp_path / "m.safetensors")
    S.save_safetensors(path, tensors)
    lazy = S.LazySafetensorsFile(path)
    assert set(lazy.keys()) == set(tensors)
    assert np.array_equal(lazy.get("ids"), tensors["ids"])
    subset = S.load_safetensors(path, names=["layer/kernel"])
    assert list(subset) == ["layer/kernel"]
    assert np.array_equal(subset["layer/kernel"], tensors["layer/kernel"])


# ---------------------------------------------------------------------------
# staging ring
# ---------------------------------------------------------------------------


def test_ring_fifo_under_backpressure():
    with native.StagingRing(3, 256) as ring:
        results = []

        def producer():
            for i in range(50):
                slot = ring.acquire()
                slot[:4] = np.frombuffer(np.int32(i).tobytes(), np.uint8)
                ring.commit(slot, 4)
            ring.close()

        t = threading.Thread(target=producer)
        t.start()
        while True:
            view = ring.pop()
            if view is None:
                break
            results.append(int(view[:4].view(np.int32)[0]))
            ring.release(view)
        t.join()
        assert results == list(range(50))


def test_ring_close_unblocks_producer():
    ring = native.StagingRing(1, 64)
    slot = ring.acquire()
    ring.commit(slot, 8)  # ring now full

    acquired = []

    def producer():
        acquired.append(ring.acquire())  # blocks until close

    t = threading.Thread(target=producer)
    t.start()
    ring.close()
    t.join(timeout=5)
    assert not t.is_alive()
    assert acquired == [None]
    ring.destroy()


# ---------------------------------------------------------------------------
# dataloader prefetch integration
# ---------------------------------------------------------------------------


def _batches(n=10):
    return [{"x": np.full((4, 8), i, np.float32), "y": np.arange(4) + 10 * i} for i in range(n)]


def test_prefetch_loader_matches_plain():
    from accelerate_tpu.data_loader import DataLoaderShard

    plain = [jax.tree.map(np.asarray, b) for b in DataLoaderShard(_batches())]
    pref = [jax.tree.map(np.asarray, b) for b in DataLoaderShard(_batches(), prefetch_size=3)]
    assert len(plain) == len(pref) == 10
    for a, b in zip(plain, pref):
        assert np.array_equal(a["x"], b["x"]) and np.array_equal(a["y"], b["y"])


def test_prefetch_loader_multiple_epochs_and_early_break():
    from accelerate_tpu.data_loader import DataLoaderShard

    dl = DataLoaderShard(_batches(), prefetch_size=2)
    assert len([b for b in dl]) == 10
    for i, _ in enumerate(dl):
        if i == 2:
            break
    # a clean run after an abandoned one still yields everything, in order
    xs = [int(np.asarray(b["x"])[0, 0]) for b in dl]
    assert xs == list(range(10))


def test_prefetch_oversized_batch_falls_back():
    """Batches bigger than the slot ride the descriptor queue (raw path)."""
    from accelerate_tpu.data_loader import _RingPrefetcher

    batches = [
        {"x": np.full((8,), 1, np.float32)},
        {"x": np.random.rand(600_000).astype(np.float32)},  # > 1.5x first batch
        {"x": np.full((8,), 3, np.float32)},
    ]
    got = list(_RingPrefetcher(batches, lambda b: jax.device_put(b), depth=2))
    assert len(got) == 3
    assert np.asarray(got[1]["x"]).shape == (600_000,)
    assert float(np.asarray(got[2]["x"])[0]) == 3.0


def test_prefetch_propagates_producer_error():
    from accelerate_tpu.data_loader import DataLoaderShard

    def gen():
        yield {"x": np.zeros(4, np.float32)}
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(DataLoaderShard(gen(), prefetch_size=2))
