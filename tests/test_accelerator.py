"""Accelerator end-to-end tests: the golden-parity strategy from the reference
(test_utils/scripts/test_script.py training_check :449 — single-process
baseline vs distributed/precision configs must produce identical or
near-identical weights) plus grad-accumulation parity (test_sync.py :207)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
from accelerate_tpu.test_utils.training import (
    make_regression_loader,
    regression_init_params,
    regression_loss_fn,
)
from accelerate_tpu.utils.dataclasses import (
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    ShardingStrategy,
)


def _train(accelerator, n_epochs=10, lr=0.1, max_grad_norm=None, batch_size=16, accum=False):
    dl = accelerator.prepare(make_regression_loader(batch_size=batch_size))
    tx = accelerator.prepare(optax.sgd(lr))
    params = regression_init_params()
    state = accelerator.create_train_state(params, tx)
    step = accelerator.prepare_train_step(regression_loss_fn, max_grad_norm=max_grad_norm)
    losses = []
    for _ in range(n_epochs):
        for batch in dl:
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    return state, losses


def test_single_device_training_converges():
    acc = Accelerator()
    state, losses = _train(acc)
    assert losses[-1] < losses[0]
    assert float(state.params["a"]) == pytest.approx(2.0, abs=0.3)
    assert float(state.params["b"]) == pytest.approx(3.0, abs=0.3)
    assert int(state.step) == 40


def test_dp_sharded_matches_baseline():
    # golden parity: dp-sharded run produces the same weights as single-logic run
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    state, _ = _train(acc, n_epochs=2)
    a_sharded, b_sharded = float(state.params["a"]), float(state.params["b"])

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    # baseline: a manual optax loop (device-free single-logic run)
    params = regression_init_params()
    tx = optax.sgd(0.1)
    opt_state = tx.init(params)
    dl = make_regression_loader(batch_size=16)
    for _ in range(2):
        for batch in dl:
            np_batch = {"x": jnp.asarray(batch["x"].numpy()), "y": jnp.asarray(batch["y"].numpy())}
            grads = jax.grad(regression_loss_fn)(params, np_batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(a_sharded, float(params["a"]), rtol=1e-5)
    np.testing.assert_allclose(b_sharded, float(params["b"]), rtol=1e-5)


def test_grad_dtype_bf16_trains_close_to_fp32():
    """grad_dtype='bf16' (compute-width grads wrt the bf16 param copy) must
    track the fp32-grad run: same convergence target, grads born bf16."""
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    acc = Accelerator(mixed_precision="bf16",
                      kwargs_handlers=[GradSyncKwargs(grad_dtype="bf16")])
    captured = {}

    def spying_loss(params, batch):
        captured["param_dtype"] = jax.tree_util.tree_leaves(params)[0].dtype
        return regression_loss_fn(params, batch)

    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), acc.prepare(optax.sgd(0.1)))
    step = acc.prepare_train_step(spying_loss, max_grad_norm=1.0)
    for _ in range(10):
        for batch in dl:
            state, metrics = step(state, batch)
    # the loss fn saw the compute-width copy (so its grads are bf16)
    assert captured["param_dtype"] == jnp.bfloat16
    # masters stay fp32 and converge to the same target as the fp32-grad run
    assert jax.tree_util.tree_leaves(state.params)[0].dtype == jnp.float32
    assert float(state.params["a"]) == pytest.approx(2.0, abs=0.3)
    assert float(state.params["b"]) == pytest.approx(3.0, abs=0.3)


def test_grad_dtype_rejects_fp16_scaling():
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    acc = Accelerator(mixed_precision="fp16",
                      kwargs_handlers=[GradSyncKwargs(grad_dtype="bf16")])
    with pytest.raises(ValueError, match="grad_dtype"):
        acc.prepare_train_step(regression_loss_fn)


def test_average_grads_false_gives_sum_semantics():
    """average_grads=False (DDP sum semantics): the optimizer sees the
    dp-world multiple of the implicit global-mean gradient (ADVICE r4)."""
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    def one_step(average):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        acc = Accelerator(
            parallelism_config=ParallelismConfig(dp_shard_size=8),
            fsdp_plugin=FullyShardedDataParallelPlugin(
                sharding_strategy=ShardingStrategy.NO_SHARD
            ),
            kwargs_handlers=[GradSyncKwargs(average_grads=average)],
        )
        state = acc.create_train_state(regression_init_params(), acc.prepare(optax.sgd(1.0)))
        step = acc.prepare_train_step(regression_loss_fn)
        batch = next(iter(acc.prepare(make_regression_loader(batch_size=16))))
        new_state, _ = step(state, batch)
        p0 = regression_init_params()
        return {k: float(new_state.params[k]) - float(p0[k]) for k in p0}

    d_mean = one_step(True)
    d_sum = one_step(False)
    assert any(abs(v) > 1e-6 for v in d_mean.values())
    for k in d_mean:
        np.testing.assert_allclose(d_sum[k], 8 * d_mean[k], rtol=1e-4)


def test_gradient_accumulation_in_step_parity():
    # accum over k microbatches == one big batch (SGD linearity)
    acc = Accelerator(gradient_accumulation_steps=4)
    state, _ = _train(acc, n_epochs=1, batch_size=16)
    a_accum = float(state.params["a"])

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2 = Accelerator()
    state2, _ = _train(acc2, n_epochs=1, batch_size=16)
    np.testing.assert_allclose(a_accum, float(state2.params["a"]), rtol=1e-5)


def test_gradient_accumulation_across_steps():
    plugin = GradientAccumulationPlugin(num_steps=2, mode="across_steps")
    acc = Accelerator(gradient_accumulation_plugin=plugin)
    dl = acc.prepare(make_regression_loader(batch_size=8))
    tx = acc.prepare(optax.sgd(0.1))
    state = acc.create_train_state(regression_init_params(), tx)
    step = acc.prepare_train_step(regression_loss_fn)
    params_before = float(state.params["a"])
    batches = list(dl)
    state, m = step(state, batches[0])
    # first microstep: params unchanged, grads buffered
    assert float(state.params["a"]) == params_before
    assert int(state.accum_step) == 1
    state, m = step(state, batches[1])
    assert float(state.params["a"]) != params_before
    assert int(state.accum_step) == 0


def test_fsdp_shards_params_and_opt_state(mesh8):
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0),
    )
    params = {"w": jnp.ones((64, 4)), "b": jnp.ones((4,)), "tiny": jnp.ones((16, 4))}
    tx = optax.adam(1e-3)
    state = acc.create_train_state(params, tx)
    w_spec = state.params["w"].sharding.spec
    assert w_spec == P("dp_shard", None) or w_spec == P(("dp_shard",), None)
    # adam moments inherit the param sharding (ZeRO property)
    mu_w = state.opt_state[0].mu["w"]
    assert mu_w.sharding.spec == w_spec
    # small scalar-ish params can't shard evenly -> b stays replicated on dim0 only if divisible
    assert state.params["b"].sharding.spec in (P("dp_shard"), P(None), P())
    # sub-tile shards (16/8 = 2 rows < the 8-sublane tile) replicate instead
    # of sharding — the plan never assigns a spec the partitioner would have
    # to pad/reshard every step
    assert state.params["tiny"].sharding.spec in (P(None, None), P())


def test_hsdp_replicas_stay_bit_identical():
    """dp_replicate x dp_shard (HSDP): after a step, devices differing only
    in their replicate coordinate hold identical bytes — the cross-replica
    grad psum is what this pins (the dryrun_multichip HSDP leg, as a unit)."""
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_replicate_size=2, dp_shard_size=4),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0),
    )
    params = {"w": jnp.ones((64, 8)) * 0.1, "b": jnp.zeros((8,))}
    state = acc.create_train_state(params, acc.prepare(optax.sgd(0.1)))

    def loss(p, batch):
        return jnp.mean((batch["x"] @ p["w"] @ p["b"][:, None] - batch["y"]) ** 2)

    step = acc.prepare_train_step(loss)
    rng = np.random.default_rng(0)
    batch = {"x": jnp.asarray(rng.normal(size=(16, 64)), jnp.float32),
             "y": jnp.asarray(rng.normal(size=(16, 1)), jnp.float32)}
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    coord_of = {d: tuple(i) for i, d in np.ndenumerate(acc.mesh.devices)}
    rep_axis = acc.mesh.axis_names.index("dp_replicate")
    by_pos = {}
    for shard in state.params["w"].addressable_shards:
        c = list(coord_of[shard.device])
        c[rep_axis] = -1
        by_pos.setdefault(tuple(c), []).append(np.asarray(shard.data))
    assert any(len(v) > 1 for v in by_pos.values())  # replicas actually exist
    for datas in by_pos.values():
        for other in datas[1:]:
            np.testing.assert_array_equal(datas[0], other)


@pytest.mark.slow
def test_cp_params_replicated_moments_joint_sharded():
    """Under cp, params consumed inside the ring shard_map stay
    cp-replicated (no per-step replicate-then-reshard churn) while the adam
    moments keep the joint (dp_shard, cp) ZeRO sharding (VERDICT r1 weak #1)."""
    acc = Accelerator(
        parallelism_config=ParallelismConfig(cp_size=2, dp_shard_size=4),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0),
    )
    params = {"w": jnp.ones((64, 128))}
    state = acc.create_train_state(params, optax.adam(1e-3))
    w_spec = state.params["w"].sharding.spec
    assert "cp" not in str(w_spec)
    assert "dp_shard" in str(w_spec)
    mu_spec = state.opt_state[0].mu["w"].sharding.spec
    assert "cp" in str(mu_spec) and "dp_shard" in str(mu_spec)


def test_tp_sharding_rules():
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2))
    params = {"layers_0": {"q_proj": {"kernel": jnp.ones((16, 8))}, "o_proj": {"kernel": jnp.ones((8, 16))}}}
    state = acc.create_train_state(params, optax.sgd(0.1))
    q = state.params["layers_0"]["q_proj"]["kernel"]
    o = state.params["layers_0"]["o_proj"]["kernel"]
    assert q.sharding.spec[1] == "tp" or q.sharding.spec[1] == ("tp",)
    assert o.sharding.spec[0] == "tp" or o.sharding.spec[0] == ("tp",)


def test_fp16_loss_scaling_step():
    # torch-GradScaler semantics: the 2^16 initial scale overflows on early
    # steps, the scale backs off (x0.5) and overflowed steps skip the update
    # (reference optimizer.py:163-177, scheduler hold :66-68)
    acc = Accelerator(mixed_precision="fp16")
    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.01))
    assert state.loss_scale is not None
    step = acc.prepare_train_step(regression_loss_fn)
    a0 = float(state.params["a"])
    overflowed = stepped = False
    for _ in range(3):
        for batch in dl:
            prev_a = float(state.params["a"])
            state, metrics = step(state, batch)
            if not bool(metrics["grads_finite"]):
                overflowed = True
                assert float(state.params["a"]) == prev_a  # skipped step
            else:
                stepped = True
            assert np.isfinite(float(metrics["loss"]))
    assert overflowed and stepped
    assert float(state.loss_scale.scale) < 2.0**16
    assert float(state.params["a"]) != a0


def test_bf16_policy_applied():
    acc = Accelerator(mixed_precision="bf16")
    seen_dtypes = []

    def probing_loss(params, batch):
        seen_dtypes.append(params["a"].dtype)
        return regression_loss_fn(params, batch)

    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))
    step = acc.prepare_train_step(probing_loss)
    state, _ = step(state, next(iter(dl)))
    assert seen_dtypes[0] == jnp.bfloat16
    assert state.params["a"].dtype == jnp.float32  # master weights stay fp32


def test_max_grad_norm_clipping():
    acc = Accelerator()
    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.sgd(1.0))
    step = acc.prepare_train_step(regression_loss_fn, max_grad_norm=0.001)
    before = float(state.params["a"])
    state, metrics = step(state, next(iter(dl)))
    # update magnitude bounded by lr * max_norm
    assert abs(float(state.params["a"]) - before) <= 0.0011


def test_clip_grad_norm_eager():
    acc = Accelerator()
    grads = {"w": jnp.full((4,), 10.0)}
    clipped, norm = acc.clip_grad_norm_(grads, max_norm=1.0)
    assert float(norm) == pytest.approx(20.0)
    assert float(global_norm_of(clipped)) == pytest.approx(1.0, rel=1e-3)


def global_norm_of(tree):
    from accelerate_tpu.accelerator import global_norm

    return global_norm(tree)


def test_backward_raises_with_guidance():
    acc = Accelerator()
    with pytest.raises(RuntimeError, match="prepare_train_step"):
        acc.backward(jnp.float32(1.0))


def test_optimizer_step_raises_with_guidance():
    acc = Accelerator()
    opt = acc.prepare(optax.sgd(0.1))
    with pytest.raises(RuntimeError, match="train step"):
        opt.step()


def test_prepare_preserves_order_and_types():
    acc = Accelerator()
    dl, tx, sched = acc.prepare(make_regression_loader(), optax.adam(1e-3), optax.linear_schedule(1e-3, 0.0, 100))
    from accelerate_tpu.data_loader import DataLoaderShard
    from accelerate_tpu.optimizer import AcceleratedOptimizer
    from accelerate_tpu.scheduler import AcceleratedScheduler

    assert isinstance(dl, DataLoaderShard)
    assert isinstance(tx, AcceleratedOptimizer)
    assert isinstance(sched, AcceleratedScheduler)


def test_scheduler_stepping():
    acc = Accelerator()
    sched = acc.prepare(optax.linear_schedule(1.0, 0.0, 10))
    sched.step()
    assert sched._step_count == 1
    assert sched.get_last_lr()[0] == pytest.approx(1.0)


def test_gather_for_metrics_drops_duplicates():
    acc = Accelerator()
    gs = acc.gradient_state

    class FakeDL:
        end_of_dataloader = True
        remainder = 5

    gs._add_dataloader(FakeDL())
    out = acc.gather_for_metrics(np.arange(8))
    assert out.tolist() == [0, 1, 2, 3, 4]
    gs._remove_dataloader(gs.active_dataloader)


def test_accumulate_context_flags():
    plugin = GradientAccumulationPlugin(num_steps=2, mode="across_steps")
    acc = Accelerator(gradient_accumulation_plugin=plugin)
    with acc.accumulate():
        assert not acc.sync_gradients
    with acc.accumulate():
        assert acc.sync_gradients


def test_accumulate_wrapping_prepared_step_counts_once():
    """The reference loop shape (`with accelerator.accumulate(): step(...)`)
    advances step_count once per batch, and sync_gradients follows the
    across_steps parity correctly (VERDICT r1 weak #5)."""
    plugin = GradientAccumulationPlugin(num_steps=2, mode="across_steps")
    acc = Accelerator(gradient_accumulation_plugin=plugin)
    tx = acc.prepare(optax.sgd(0.1))
    state = acc.create_train_state(regression_init_params(), tx)
    step = acc.prepare_train_step(regression_loss_fn)
    dl = make_regression_loader(batch_size=16)
    syncs = []
    for i, batch in enumerate(dl):
        if i >= 4:
            break
        b = {"x": jnp.asarray(batch["x"].numpy()), "y": jnp.asarray(batch["y"].numpy())}
        with acc.accumulate():
            state, _ = step(state, b)
            syncs.append(bool(acc.sync_gradients))
    assert acc.step_count == 4  # not 8
    assert syncs == [False, True, False, True]


def test_prepare_passes_through_non_schedule_callable(caplog):
    """A user's 1-arg callable (collate_fn/loss_fn) must not be silently
    wrapped as a scheduler (VERDICT r1 weak #3)."""
    import logging as _logging

    from accelerate_tpu.scheduler import AcceleratedScheduler

    acc = Accelerator()

    def collate(batch):
        return batch

    with caplog.at_level(_logging.WARNING, logger="accelerate_tpu.accelerator"):
        out = acc.prepare(collate)
    assert out is collate
    assert any("prepare_scheduler" in r.message for r in caplog.records)
    # optax schedules still auto-wrap; explicit marker works for custom ones
    assert isinstance(acc.prepare(optax.linear_schedule(1.0, 0.0, 10)), AcceleratedScheduler)

    def my_schedule(step):
        return 0.1

    my_schedule.is_schedule = True
    assert isinstance(acc.prepare(my_schedule), AcceleratedScheduler)


def test_gather_for_metrics_unsliceable_warns_not_silent(caplog, monkeypatch):
    """An un-sliceable gathered result keeps the full data with a warning
    instead of silently swallowing the error (VERDICT r1 weak #2), and a
    non-slicing bug (e.g. ValueError) propagates instead of being eaten."""
    import logging as _logging

    from accelerate_tpu import accelerator as accel_mod

    acc = Accelerator()
    gs = acc.gradient_state

    class FakeDL:
        end_of_dataloader = True
        remainder = 5

    gs._add_dataloader(FakeDL())
    try:
        def _unsliceable(func, data, *a, **k):
            raise TypeError("object is not subscriptable")

        monkeypatch.setattr(accel_mod.ops, "recursively_apply", _unsliceable)
        with caplog.at_level(_logging.WARNING, logger="accelerate_tpu.accelerator"):
            out = acc.gather_for_metrics(np.arange(8))
        assert any("duplicate tail" in r.message for r in caplog.records)
        assert np.asarray(out).shape == (8,)  # full data, not truncated

        def _bug(func, data, *a, **k):
            raise ValueError("genuine bug")

        monkeypatch.setattr(accel_mod.ops, "recursively_apply", _bug)
        with pytest.raises(ValueError, match="genuine bug"):
            acc.gather_for_metrics(np.arange(8))
    finally:
        gs._remove_dataloader(gs.active_dataloader)


def test_eval_step():
    acc = Accelerator(mixed_precision="bf16")
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))

    def eval_fn(params, batch):
        return params["a"] * batch["x"] + params["b"]

    estep = acc.prepare_eval_step(eval_fn)
    out = estep(state.params, {"x": jnp.ones(4)})
    assert out.shape == (4,)


def test_set_and_check_trigger():
    acc = Accelerator()
    assert not acc.check_trigger()
    acc.set_trigger()
    assert acc.check_trigger()
    assert not acc.check_trigger()  # reset after firing


def test_train_step_has_aux_simple():
    """Aux from the loss (e.g. batch-norm stats) reaches metrics['aux']."""
    acc = Accelerator()
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))

    def loss_fn(params, batch):
        pred = params["a"] * batch["x"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {"pred_mean": jnp.mean(pred)}

    step = acc.prepare_train_step(loss_fn, has_aux=True)
    batch = {"x": jnp.ones(8), "y": jnp.full(8, 5.0)}
    state, metrics = step(state, batch)
    assert "aux" in metrics and np.isfinite(float(metrics["aux"]["pred_mean"]))


@pytest.mark.slow
def test_train_step_has_aux_with_accumulation():
    """Aux rides the microbatch scan carry: last microbatch's aux returned."""
    acc = Accelerator(gradient_accumulation_steps=4)
    state = acc.create_train_state(regression_init_params(), optax.sgd(0.1))

    def loss_fn(params, batch):
        pred = params["a"] * batch["x"] + params["b"]
        # aux identifies the microbatch so the test can assert "last wins"
        return jnp.mean((pred - batch["y"]) ** 2), {"x_first": batch["x"][0]}

    step = acc.prepare_train_step(loss_fn, has_aux=True)
    x = jnp.arange(16.0)  # microbatches of 4: last starts at 12
    state, metrics = step(state, {"x": x, "y": jnp.zeros(16)})
    assert float(metrics["aux"]["x_first"]) == 12.0


@pytest.mark.slow
def test_grad_accum_buffers_shard_like_params():
    """across_steps accumulation buffers must inherit FSDP shardings — an
    uncommitted/replicated grad_accum would be a full gradient copy per
    device (regression for the scalar-replication pin)."""
    from accelerate_tpu.utils.dataclasses import GradientAccumulationPlugin

    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=2, mode="across_steps"),
    )
    params = {"w": jnp.zeros((64, 16), jnp.float32), "b": jnp.zeros((64,), jnp.float32)}
    state = acc.create_train_state(params, optax.sgd(0.1))
    assert state.grad_accum is not None
    w_spec = state.params["w"].sharding.spec
    accum_spec = state.grad_accum["w"].sharding.spec
    assert accum_spec == w_spec, (accum_spec, w_spec)
    # scalars replicated on the mesh (not single-device)
    assert state.step.sharding.spec == jax.sharding.PartitionSpec()


@pytest.mark.slow
def test_maybe_context_parallel_shards_buffers():
    """CP per-step buffer sharding (reference maybe_context_parallel :4076):
    yields zigzag-reordered, cp-sharded buffers; no-op without cp."""
    from accelerate_tpu.parallel.context_parallel import zigzag_unshard

    acc = Accelerator(parallelism_config=ParallelismConfig(cp_size=8))
    ids = np.arange(2 * 32).reshape(2, 32).astype(np.int32)
    with acc.maybe_context_parallel(buffers=[ids, ids], buffer_seq_dims=[1, 1]) as (a, b):
        assert a.sharding.spec == P(None, "cp")
        # zigzag round-trips back to the original ordering
        np.testing.assert_array_equal(zigzag_unshard(np.asarray(a), 8), ids)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_maybe_context_parallel_noop_without_cp():
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    ids = np.ones((2, 16), np.int32)
    with acc.maybe_context_parallel(buffers=[ids], buffer_seq_dims=[1]) as (out,):
        np.testing.assert_array_equal(np.asarray(out), ids)
    with acc.maybe_context_parallel() as empty:
        assert empty == []
