"""The resilience acceptance matrix (docs/resilience.md), green on CPU:

(a) SIGTERM mid-run → emergency checkpoint → a FRESH PROCESS resumes with
    bit-exact params/opt-state/RNG/dataloader position vs an uninterrupted
    run (subprocess e2e);
(b) corrupt/truncated latest checkpoint → ``load_state`` falls back to the
    newest valid one with a warning, no crash;
(c) injected NaN grad → step skipped, params bitwise unchanged, counters
    advance, abort after K consecutive;
(d) transient transfer failure → bounded retry/backoff, result identical to
    the no-fault run;

plus the satellites: async-save orphan flush at interpreter exit, retention
GC vs the fallback scan, mid-epoch dataloader resume bit-parity, and the
fault-plan/goodput machinery itself."""

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.checkpointing import (
    CheckpointCorruptError,
    list_checkpoints,
    verify_checkpoint,
    write_checkpoint_manifest,
)
from accelerate_tpu.resilience import (
    CORRUPTION_MODES,
    FAULT_KINDS,
    RESUME_EXIT_CODE,
    FaultEvent,
    FaultPlan,
    GoodputTracker,
    InjectedTransferError,
    NanGuardAbort,
    PeerSchemaError,
    PeerSnapshotter,
    PreemptionHandler,
    RankLostError,
    RetryPolicy,
    capture_host_snapshot,
    check_snapshot_schemas,
    corrupt_checkpoint,
    fault_plan,
    goodput_accounting,
    install_fault_plan,
    peer_ckpt_accounting,
    restore_host_snapshot,
    snapshot_schema,
    with_retries,
)
from accelerate_tpu.resilience.faults import KIND_DEFAULT_SITE
from accelerate_tpu.test_utils.training import (
    make_regression_loader,
    regression_init_params,
    regression_loss_fn,
)
from accelerate_tpu.utils.dataclasses import ProjectConfiguration, ResiliencePlugin

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _disarm_fault_plan():
    """No fault plan may leak across tests (the hooks are process-global)."""
    yield
    install_fault_plan(None)


def _setup(tmp_path, *, plugin=None, total_limit=None):
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True,
            total_limit=total_limit,
        ),
        resilience_plugin=plugin,
    )
    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    step = acc.prepare_train_step(regression_loss_fn)
    return acc, dl, state, step


def _bytes_of(x) -> bytes:
    return np.asarray(x).tobytes()


# ---------------------------------------------------------------------------
# (a) SIGTERM → emergency checkpoint → fresh-process resume, bit-exact
# ---------------------------------------------------------------------------


_TRAIN_SCRIPT = textwrap.dedent('''
    """Fault-matrix training subprocess: N regression steps with periodic-free
    checkpointing discipline — resume state comes only from the emergency
    checkpoint a preemption writes."""
    import json, random, sys

    import numpy as np
    import optax
    import jax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils.training import (
        make_regression_loader, regression_init_params, regression_loss_fn,
    )
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration, ResiliencePlugin
    from accelerate_tpu.utils.random import set_seed

    project_dir, result_file = sys.argv[1], sys.argv[2]
    TOTAL_STEPS = 6  # epoch = 4 batches, so the run crosses an epoch boundary

    set_seed(123)  # a known host-RNG stream (captured/restored by checkpoints)
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=project_dir, automatic_checkpoint_naming=True
        ),
        resilience_plugin=ResiliencePlugin(handle_preemption=True, nan_guard=False),
    )
    dl = acc.prepare(make_regression_loader(batch_size=16))
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    state = acc.maybe_resume(train_state=template)
    if state is None:
        state = template
    step = acc.prepare_train_step(regression_loss_fn)

    consumed = []  # batch fingerprints, in training order
    while acc.step_count < TOTAL_STEPS:
        for batch in dl:
            consumed.append(np.asarray(batch["x"]).tobytes().hex())
            state, metrics = step(state, batch)
            if acc.step_count >= TOTAL_STEPS:
                break

    acc.end_training()
    result = {
        "a": np.asarray(state.params["a"]).tobytes().hex(),
        "b": np.asarray(state.params["b"]).tobytes().hex(),
        "mu_a": np.asarray(state.opt_state[0].mu["a"]).tobytes().hex(),
        "nu_a": np.asarray(state.opt_state[0].nu["a"]).tobytes().hex(),
        "step": int(state.step),
        "step_count": acc.step_count,
        "rng_key": np.asarray(jax.random.key_data(state.rng)).tobytes().hex(),
        "py_rand": random.random(),
        "np_rand": float(np.random.rand()),
        "restarts": acc.goodput.restarts,
        "consumed": consumed,
    }
    with open(result_file, "w") as f:
        json.dump(result, f)
''')


def _run_subprocess(script: str, args, extra_env=None, expect_code=0):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra_env or {})
    out = subprocess.run(
        [sys.executable, "-c", script, *map(str, args)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert out.returncode == expect_code, (
        f"exit {out.returncode} (want {expect_code})\n{out.stderr[-3000:]}"
    )
    return out


def test_sigterm_preemption_fresh_process_resume_bit_exact(tmp_path):
    """Acceptance (a): the whole flow across REAL process boundaries.  The
    preempted run gets a SIGTERM during step 3 (via the deterministic fault
    plan → os.kill through the installed handler), exits 75 after writing
    the emergency checkpoint; a fresh process auto-resumes and must finish
    with bit-identical params/opt-state/RNG — and the concatenated batch
    stream must equal the uninterrupted run's exactly."""
    clean_dir, faulted_dir = tmp_path / "clean", tmp_path / "faulted"
    clean_res, res1, res2 = (tmp_path / f"r{i}.json" for i in range(3))

    _run_subprocess(_TRAIN_SCRIPT, [clean_dir, clean_res])
    clean = json.loads(clean_res.read_text())
    assert clean["step_count"] == 6 and len(clean["consumed"]) == 6

    # run 1: preempted during step 3 → resume exit code, no result file
    _run_subprocess(
        _TRAIN_SCRIPT, [faulted_dir, res1],
        extra_env={"ACCELERATE_FAULT_PLAN": json.dumps(
            {"events": [{"kind": "preempt", "at": 3}]}
        )},
        expect_code=RESUME_EXIT_CODE,
    )
    assert not res1.exists()
    ckpts = list_checkpoints(str(faulted_dir))
    assert len(ckpts) == 1, "exactly the emergency checkpoint"
    ok, problems = verify_checkpoint(ckpts[0])
    assert ok, problems

    # run 2: fresh process, auto-resume, finish the budget
    _run_subprocess(_TRAIN_SCRIPT, [faulted_dir, res2])
    resumed = json.loads(res2.read_text())

    assert resumed["restarts"] == 1
    assert resumed["step"] == clean["step"] == 6
    # bit-exact state: params, optimizer moments, the traced RNG key
    for key in ("a", "b", "mu_a", "nu_a", "rng_key"):
        assert resumed[key] == clean[key], key
    # host RNG streams restored from the emergency checkpoint
    assert resumed["py_rand"] == clean["py_rand"]
    assert resumed["np_rand"] == clean["np_rand"]
    # dataloader position: 3 batches before the preemption + 3 after == the
    # uninterrupted stream, nothing replayed, nothing skipped
    assert len(resumed["consumed"]) == 3
    assert clean["consumed"][3:] == resumed["consumed"]


def test_preemption_in_process_exit_and_emergency_checkpoint(tmp_path):
    """The in-process half of (a): request → boundary stop → verified
    emergency checkpoint → SystemExit(75) → resume restores the state."""
    plugin = ResiliencePlugin(handle_preemption=True, nan_guard=False)
    acc, dl, state, step = _setup(tmp_path, plugin=plugin)
    batch = next(iter(dl))
    state, _ = step(state, batch)
    acc._preemption.request()
    with pytest.raises(SystemExit) as exc:
        step(state, batch)
    assert exc.value.code == RESUME_EXIT_CODE
    assert acc.goodput.preemptions == 1
    ckpts = list_checkpoints(str(tmp_path))
    assert len(ckpts) == 1
    ok, problems = verify_checkpoint(ckpts[0])
    assert ok, problems

    acc._preemption.clear()
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    restored = acc.maybe_resume(train_state=template)
    assert restored is not None and int(restored.step) == 2
    assert acc.goodput.restarts == 1


def test_preemption_handler_real_signal_delivery():
    import signal

    handler = PreemptionHandler(("SIGTERM",)).install()
    try:
        assert not handler.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert handler.requested
        handler.clear()
        assert not handler.requested
    finally:
        handler.uninstall()


# ---------------------------------------------------------------------------
# (b) corrupt latest checkpoint → verified fallback
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["truncate", "bitflip"])
def test_corrupt_latest_falls_back_to_newest_valid(tmp_path, mode, caplog):
    acc, dl, state, step = _setup(tmp_path)
    for batch in dl:
        state, _ = step(state, batch)
        acc.save_state(train_state=state)
    ckpts = list_checkpoints(str(tmp_path))
    assert len(ckpts) >= 2
    good_state_a = None
    # remember the params the second-newest checkpoint holds
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    good_state_a = float(np.asarray(acc.load_state(ckpts[-2], train_state=template).params["a"]))

    corrupt_checkpoint(ckpts[-1], mode=mode, seed=3)
    ok, problems = verify_checkpoint(ckpts[-1])
    assert not ok and problems

    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    with caplog.at_level("WARNING"):
        restored = acc.load_state(train_state=template)  # auto path: no crash
    assert any("failed verification" in r.message for r in caplog.records)
    assert float(np.asarray(restored.params["a"])) == good_state_a


def test_corrupt_explicit_dir_raises(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    ckpt = acc.save_state(train_state=state)
    corrupt_checkpoint(ckpt, mode="truncate", seed=0)
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    with pytest.raises(CheckpointCorruptError):
        acc.load_state(ckpt, train_state=template)


def test_all_checkpoints_corrupt_raises_loudly(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    for _ in range(2):
        acc.save_state(train_state=state)
    for c in list_checkpoints(str(tmp_path)):
        corrupt_checkpoint(c, mode="truncate", seed=1)
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    with pytest.raises(CheckpointCorruptError, match="no valid checkpoint"):
        acc.load_state(train_state=template)


def test_verify_checkpoint_contract(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    ckpt = Path(acc.save_state(train_state=state))
    ok, problems = verify_checkpoint(ckpt)
    assert ok and not problems
    # legacy dir (no manifest): valid-but-unverified, with a note
    manifest = ckpt / "checkpoint_manifest.json"
    manifest.unlink()
    ok, problems = verify_checkpoint(ckpt)
    assert ok and "no manifest" in problems[0]
    write_checkpoint_manifest(ckpt)
    # a deleted payload file is a hard failure
    victim = next(p for p in sorted((ckpt / "train_state").rglob("*")) if p.is_file())
    victim.unlink()
    ok, problems = verify_checkpoint(ckpt)
    assert not ok and any("missing file" in p for p in problems)
    # so are .tmp staging dirs and absent paths
    assert verify_checkpoint(str(ckpt) + ".tmp")[0] is False
    assert verify_checkpoint(tmp_path / "nope")[0] is False


def test_legacy_torn_checkpoint_falls_back_without_manifest(tmp_path):
    """A pre-resilience (manifest-less) torn checkpoint passes verification
    as 'unverified' but fails to restore — the auto-resume scan must walk on
    to the previous candidate instead of crashing (the FileNotFoundError a
    missing shard raises is a restore failure like any other here)."""
    acc, dl, state, step = _setup(tmp_path)
    state, _ = step(state, next(iter(dl)))
    acc.save_state(train_state=state)
    a_valid = float(np.asarray(state.params["a"]))
    acc.save_state(train_state=state)
    ckpts = [Path(c) for c in list_checkpoints(str(tmp_path))]
    for c in ckpts:  # both legacy: no manifests to verify against
        (c / "checkpoint_manifest.json").unlink()
    # tear the newest: its train_state payload disappears entirely
    import shutil
    shutil.rmtree(ckpts[-1] / "train_state")

    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    restored = acc.load_state(train_state=template)  # auto path: no crash
    assert float(np.asarray(restored.params["a"])) == a_valid


def test_preemption_exit_code_survives_failed_emergency_save(tmp_path):
    """An I/O failure during the emergency save (retry budget exhausted)
    must not turn the preemption into a crash code: the supervisor contract
    is 're-queue on 75', and older checkpoints still exist to resume from."""
    plugin = ResiliencePlugin(handle_preemption=True, nan_guard=False,
                              io_retries=1, io_backoff_s=0.001)
    acc, dl, state, step = _setup(tmp_path, plugin=plugin)
    batch = next(iter(dl))
    state, _ = step(state, batch)
    acc._preemption.request()
    # every checkpoint-I/O attempt fails — past the bounded budget
    with fault_plan(FaultPlan([FaultEvent("transfer", at=1, count=10,
                                          site="checkpoint_io")])):
        with pytest.raises(SystemExit) as exc:
            step(state, batch)
    assert exc.value.code == RESUME_EXIT_CODE


def test_fault_plan_injected_corruption_via_post_save_hook(tmp_path):
    """corrupt_ckpt events fire through the real save path (post-publish)."""
    acc, dl, state, step = _setup(tmp_path)
    with fault_plan(FaultPlan([FaultEvent("corrupt_ckpt", at=1, mode="bitflip")])):
        ckpt = acc.save_state(train_state=state)
    ok, problems = verify_checkpoint(ckpt)
    assert not ok and any("checksum mismatch" in p for p in problems)


# ---------------------------------------------------------------------------
# (c) NaN guard
# ---------------------------------------------------------------------------


def _guard_setup(tmp_path, max_consecutive=3):
    plugin = ResiliencePlugin(
        nan_guard=True, max_consecutive_nan_skips=max_consecutive,
        handle_preemption=False,
    )
    return _setup(tmp_path, plugin=plugin)


def test_nan_guard_skips_step_params_bitwise_unchanged(tmp_path):
    acc, dl, state, step = _guard_setup(tmp_path)
    batch = next(iter(dl))
    with fault_plan(FaultPlan([FaultEvent("nan_grad", at=2)])):
        state, m = step(state, batch)
        assert bool(m["nan_skipped"]) is False
        params_before = {k: _bytes_of(v) for k, v in state.params.items()}
        mu_before = _bytes_of(state.opt_state[0].mu["a"])
        state, m = step(state, batch)
        # skipped: counters advance, state held bitwise
        assert bool(m["nan_skipped"]) is True
        assert int(m["nan_skips"]) == 1
        assert int(m["consecutive_nan_skips"]) == 1
        for k, v in params_before.items():
            assert _bytes_of(state.params[k]) == v, f"params[{k}] changed on a skipped step"
        assert _bytes_of(state.opt_state[0].mu["a"]) == mu_before
        # next clean step resets the consecutive counter and trains on
        state, m = step(state, batch)
        assert bool(m["nan_skipped"]) is False
        assert int(m["consecutive_nan_skips"]) == 0
        assert int(m["nan_skips"]) == 1
        assert np.isfinite(float(m["loss"]))
    assert acc.goodput.nan_skips == 1


def test_nan_guard_aborts_after_consecutive_skips(tmp_path):
    acc, dl, state, step = _guard_setup(tmp_path, max_consecutive=2)
    batch = next(iter(dl))
    with fault_plan(FaultPlan([FaultEvent("nan_grad", at=1, count=3)])):
        state, m = step(state, batch)
        assert int(m["consecutive_nan_skips"]) == 1
        with pytest.raises(NanGuardAbort, match="2 consecutive"):
            step(state, batch)


def test_nan_guard_counts_skips_with_abort_disabled(tmp_path):
    """max_consecutive_nan_skips=0 disables only the abort: skips still land
    in the goodput counters bench.py always emits."""
    acc, dl, state, step = _guard_setup(tmp_path, max_consecutive=0)
    batch = next(iter(dl))
    with fault_plan(FaultPlan([FaultEvent("nan_grad", at=1, count=2)])):
        for _ in range(3):
            state, m = step(state, batch)  # never aborts
    assert acc.goodput.nan_skips == 2
    assert int(m["nan_skips"]) == 2


def test_nan_guard_counters_survive_checkpoint_resume(tmp_path):
    acc, dl, state, step = _guard_setup(tmp_path)
    batch = next(iter(dl))
    with fault_plan(FaultPlan([FaultEvent("nan_grad", at=1)])):
        state, m = step(state, batch)
    assert int(m["nan_skips"]) == 1
    ckpt = acc.save_state(train_state=state)
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    restored = acc.load_state(ckpt, train_state=template)
    assert int(restored.guard_state["nan_skips"]) == 1


def test_nan_guard_off_keeps_state_shape(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    assert state.guard_state is None
    batch = next(iter(dl))
    state, m = step(state, batch)
    assert "nan_skipped" not in m


# ---------------------------------------------------------------------------
# (d) transient transfer failures → bounded retry, identical results
# ---------------------------------------------------------------------------


def test_layer_prefetcher_retries_transient_failures():
    from accelerate_tpu.ops.streaming import LayerPrefetcher, StreamStats

    layers = [{"w": jnp.full((4, 4), i, jnp.float32)} for i in range(4)]
    calls = []

    def fetch(i):
        calls.append(i)
        return layers[i]

    def run(plan):
        stats = StreamStats()
        pf = LayerPrefetcher(fetch, len(layers), depth=1, stats=stats,
                             retry_policy=RetryPolicy(retries=3, backoff_s=0.001))
        with fault_plan(plan):
            out = [np.asarray(pf.get(i)["w"]).copy() for i in range(len(layers))]
        return out, stats

    clean, _ = run(None)
    # two consecutive injected failures at the 2nd transfer attempt: within
    # the bounded budget, absorbed, decode identical
    faulted, stats = run(FaultPlan([FaultEvent("transfer", at=2, count=2)]))
    for a, b in zip(clean, faulted):
        np.testing.assert_array_equal(a, b)
    assert stats.transfer_retries == 2
    assert stats.overlap_report()["transfer_retries"] == 2


def test_layer_prefetcher_exhausted_budget_raises():
    from accelerate_tpu.ops.streaming import LayerPrefetcher

    pf = LayerPrefetcher(lambda i: {"w": jnp.zeros(2)}, 2,
                         retry_policy=RetryPolicy(retries=1, backoff_s=0.001))
    with fault_plan(FaultPlan([FaultEvent("transfer", at=1, count=5)])):
        with pytest.raises(InjectedTransferError):
            pf.get(0)


def test_dataloader_h2d_retry_identical_stream(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    clean = [np.asarray(b["x"]).copy() for b in dl]
    with fault_plan(FaultPlan([FaultEvent("transfer", at=2, count=2)])):
        faulted = [np.asarray(b["x"]).copy() for b in dl]
    assert len(clean) == len(faulted)
    for a, b in zip(clean, faulted):
        np.testing.assert_array_equal(a, b)
    # retries flowed into the goodput counters (the loaders carry the
    # accelerator's ResiliencePlugin budget + hook)
    assert acc.goodput.transfer_retries == 2


def test_dataloader_h2d_retry_training_identical(tmp_path):
    """The full (d) criterion: training through an injected transient H2D
    failure must produce the same result as the no-fault run."""
    acc, dl, state, step = _setup(tmp_path)
    for batch in dl:
        state, _ = step(state, batch)
    clean_a = _bytes_of(state.params["a"])

    from accelerate_tpu.state import AcceleratorState, GradientState
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2, dl2, state2, step2 = _setup(tmp_path / "f")
    with fault_plan(FaultPlan([FaultEvent("transfer", at=3)])):
        for batch in dl2:
            state2, _ = step2(state2, batch)
    assert _bytes_of(state2.params["a"]) == clean_a


def test_checkpoint_io_retry_and_goodput_counter(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    plan = FaultPlan([FaultEvent("transfer", at=1, count=2, site="checkpoint_io")])
    with fault_plan(plan):
        ckpt = acc.save_state(train_state=state)
    assert verify_checkpoint(ckpt)[0]
    assert acc.goodput.io_retries == 2
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    with fault_plan(FaultPlan([FaultEvent("transfer", at=1, site="checkpoint_io")])):
        restored = acc.load_state(ckpt, train_state=template)
    assert float(np.asarray(restored.params["a"])) == float(np.asarray(state.params["a"]))


def test_retry_budget_is_bounded_and_fatal_errors_skip_it():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise InjectedTransferError("always down")

    with pytest.raises(InjectedTransferError):
        with_retries(flaky, policy=RetryPolicy(retries=2, backoff_s=0.001))
    assert calls["n"] == 3  # 1 try + 2 bounded re-attempts, never infinite

    calls["n"] = 0

    def missing():
        calls["n"] += 1
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        with_retries(missing, policy=RetryPolicy(retries=5, backoff_s=0.001))
    assert calls["n"] == 1  # fatal: retrying cannot change the answer


# ---------------------------------------------------------------------------
# satellite: async-save orphan flush at interpreter exit
# ---------------------------------------------------------------------------


_ORPHAN_SCRIPT = textwrap.dedent('''
    """async save, then exit WITHOUT end_training/wait: the interpreter-exit
    flush must drain the write AND publish the atomic rename."""
    import sys
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.test_utils.training import regression_init_params
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    acc = Accelerator(project_config=ProjectConfiguration(
        project_dir=sys.argv[1], automatic_checkpoint_naming=True))
    state = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    acc.save_state(train_state=state, async_save=True)
    # fall off the end: no end_training(), no wait_for_checkpoint()
''')


def test_interpreter_exit_never_orphans_async_save(tmp_path):
    _run_subprocess(_ORPHAN_SCRIPT, [tmp_path])
    base = tmp_path / "checkpoints"
    tmps = list(base.glob("*.tmp"))
    assert not tmps, f"half-written staging dirs left behind: {tmps}"
    ckpts = list_checkpoints(str(tmp_path))
    assert len(ckpts) == 1
    ok, problems = verify_checkpoint(ckpts[0])
    assert ok, problems


# ---------------------------------------------------------------------------
# satellite: mid-epoch dataloader resume — bit parity with the clean run
# ---------------------------------------------------------------------------


def _torch_loader(n=32, bs=4):
    import torch
    import torch.utils.data as tud

    class DS(tud.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            return {"x": torch.arange(i * 8, (i + 1) * 8, dtype=torch.float32)}

    return tud.DataLoader(DS(), batch_size=bs, shuffle=False)


def test_shard_loader_mid_epoch_resume_bit_parity(tmp_path):
    """data_loader.py DataLoaderShard.load_state_dict: batches after a
    resume-at-batch-k must bit-match the uninterrupted run — across the
    epoch boundary too."""
    from accelerate_tpu.data_loader import prepare_data_loader

    ref_dl = prepare_data_loader(_torch_loader())
    reference = [np.asarray(b["x"]).copy() for b in ref_dl]      # epoch 0
    reference += [np.asarray(b["x"]).copy() for b in ref_dl]     # epoch 1

    live = prepare_data_loader(_torch_loader())
    it = iter(live)
    for _ in range(3):
        next(it)
    sd = live.state_dict()
    assert sd == {"batches_yielded": 3, "iteration": 0}

    resumed = prepare_data_loader(_torch_loader())
    resumed.load_state_dict(sd)
    stream = [np.asarray(b["x"]).copy() for b in resumed]        # rest of epoch 0
    stream += [np.asarray(b["x"]).copy() for b in resumed]       # full epoch 1
    assert len(stream) == len(reference) - 3
    for got, want in zip(stream, reference[3:]):
        np.testing.assert_array_equal(got, want)


def test_dispatcher_mid_epoch_resume_bit_parity():
    """Same contract through DataLoaderDispatcher.load_state_dict."""
    from accelerate_tpu.data_loader import DataLoaderDispatcher

    reference = [np.asarray(b["x"]).copy() for b in DataLoaderDispatcher(_torch_loader())]

    live = DataLoaderDispatcher(_torch_loader())
    it = iter(live)
    for _ in range(5):
        next(it)
    sd = live.state_dict()
    assert sd["batches_yielded"] == 5

    resumed = DataLoaderDispatcher(_torch_loader())
    resumed.load_state_dict(sd)
    stream = [np.asarray(b["x"]).copy() for b in resumed]
    assert len(stream) == len(reference) - 5
    for got, want in zip(stream, reference[5:]):
        np.testing.assert_array_equal(got, want)


def test_mid_epoch_resume_through_checkpoint_bit_parity(tmp_path):
    """End-to-end through save_state/load_state: the restored loader's
    remaining batches bit-match the uninterrupted stream (the
    data_loader.load_state_dict path driven by the checkpoint files)."""
    acc, dl, state, step = _setup(tmp_path)
    reference = [np.asarray(b["x"]).copy() for b in dl]

    from accelerate_tpu.state import AcceleratorState, GradientState
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2, dl2, state2, step2 = _setup(tmp_path)
    it = iter(dl2)
    next(it)
    next(it)
    ckpt = acc2.save_state(train_state=state2)

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc3, dl3, state3, step3 = _setup(tmp_path)
    acc3.load_state(ckpt)
    remaining = [np.asarray(b["x"]).copy() for b in dl3]
    assert len(remaining) == len(reference) - 2
    for got, want in zip(remaining, reference[2:]):
        np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# machinery: fault plans, goodput, handler hygiene
# ---------------------------------------------------------------------------


def test_fault_plan_determinism_and_occurrence_semantics():
    plan_a = FaultPlan.from_seed(7, 50, p_preempt=0.05, p_nan=0.1, p_transfer=0.1)
    plan_b = FaultPlan.from_seed(7, 50, p_preempt=0.05, p_nan=0.1, p_transfer=0.1)
    assert plan_a.events == plan_b.events
    assert plan_a.events != FaultPlan.from_seed(8, 50, p_nan=0.1).events

    plan = FaultPlan([FaultEvent("nan_grad", at=2, count=2)])
    assert plan.fire("step") == ()
    assert [e.kind for e in plan.fire("step")] == ["nan_grad"]
    assert [e.kind for e in plan.fire("step")] == ["nan_grad"]
    assert plan.fire("step") == ()
    assert len(plan.fired) == 2

    spec = plan.to_spec()
    assert FaultPlan.from_spec(spec).events == plan.events

    with pytest.raises(ValueError):
        FaultEvent("meteor", at=1)
    with pytest.raises(ValueError):
        FaultEvent("corrupt_ckpt", mode="melt")


def test_goodput_tracker_and_predicted_model():
    t = GoodputTracker()
    assert t.report()["goodput_frac"] == 1.0
    for _ in range(10):
        t.record_step()
    t.record_nan_skip()
    t.record_restart(steps_recomputed=1)
    rep = t.report()
    assert rep["steps"] == 10 and rep["nan_skips"] == 1 and rep["restarts"] == 1
    assert rep["goodput_frac"] == pytest.approx(0.8, abs=0.01)

    pred = goodput_accounting(1.0, 100, save_overhead_s=2.0,
                              preemption_rate_per_hour=1.0)
    assert pred["kind"] == "predicted"
    assert 0.0 < pred["goodput_frac"] < 1.0
    # more frequent checkpoints under heavy preemption → better goodput
    heavy = dict(save_overhead_s=0.5, preemption_rate_per_hour=20.0)
    assert (goodput_accounting(1.0, 20, **heavy)["goodput_frac"]
            > goodput_accounting(1.0, 500, **heavy)["goodput_frac"])


def test_resilience_plugin_env_defaults(monkeypatch):
    plugin = ResiliencePlugin()
    assert plugin.nan_guard is False and plugin.handle_preemption is False
    monkeypatch.setenv("ACCELERATE_RESILIENCE", "1")
    armed = ResiliencePlugin()
    assert armed.nan_guard is True and armed.handle_preemption is True
    monkeypatch.setenv("ACCELERATE_NAN_GUARD", "0")
    mixed = ResiliencePlugin()
    assert mixed.nan_guard is False and mixed.handle_preemption is True
    with pytest.raises(ValueError):
        ResiliencePlugin(max_consecutive_nan_skips=-1)


@pytest.mark.parametrize("mode", CORRUPTION_MODES)
def test_retention_gc_vs_fallback_scan(tmp_path, mode):
    """Satellite: rank-0 GC must never delete the checkpoint a fallback
    load_state scan could still select — with the latest corrupt (every
    CORRUPTION_MODES entry), the previous valid one survives retention and
    the resume lands on it."""
    acc, dl, state, step = _setup(tmp_path, total_limit=2)
    it = iter(dl)
    state, _ = step(state, next(it))
    acc.save_state(train_state=state)          # checkpoint_0 (valid)
    a_valid = float(np.asarray(state.params["a"]))
    state, _ = step(state, next(it))
    acc.save_state(train_state=state)          # checkpoint_1
    ckpts = list_checkpoints(str(tmp_path))
    corrupt_checkpoint(ckpts[-1], mode=mode, seed=0)  # newest now corrupt

    # next save triggers GC at total_limit=2: the naive victim is
    # checkpoint_0 — but it is the only valid fallback candidate
    state, _ = step(state, next(it))
    acc.save_state(train_state=state)          # checkpoint_2
    survivors = [os.path.basename(c) for c in list_checkpoints(str(tmp_path))]
    assert "checkpoint_0" in survivors, "GC deleted the only valid fallback"

    # and once a newer valid checkpoint exists, the spared one is collectable
    state, _ = step(state, next(it))
    acc.save_state(train_state=state)          # checkpoint_3 (valid)
    survivors = [os.path.basename(c) for c in list_checkpoints(str(tmp_path))]
    assert "checkpoint_0" not in survivors
    assert "checkpoint_3" in survivors


@pytest.mark.parametrize("mode", CORRUPTION_MODES)
def test_presumed_valid_for_gc_every_corruption_mode(tmp_path, mode):
    """GC's validity oracle agrees with the full verify for every
    corruption flavor: valid → True (and stat-snapshot refreshed), then
    corrupted in place → the stat drift forces the crc re-verify → False."""
    from accelerate_tpu.checkpointing import _presumed_valid_for_gc

    acc, dl, state, step = _setup(tmp_path)
    ckpt = Path(acc.save_state(train_state=state))
    assert _presumed_valid_for_gc(ckpt) is True
    corrupt_checkpoint(ckpt, mode=mode, seed=2)
    assert verify_checkpoint(ckpt)[0] is False
    assert _presumed_valid_for_gc(ckpt) is False
    # still False on re-ask: a failed verify must not poison the snapshot
    # cache into presuming the corrupt dir valid next round
    assert _presumed_valid_for_gc(ckpt) is False


# ---------------------------------------------------------------------------
# peer-redundant hot checkpoints + the recovery ladder (single process; the
# cross-rank legs live in tests/test_train_fabric.py, slow tier)
# ---------------------------------------------------------------------------


def test_new_fault_kinds_registered():
    for kind in ("rank_loss", "straggler", "partial_ckpt"):
        assert kind in FAULT_KINDS
    assert KIND_DEFAULT_SITE["rank_loss"] == "step"
    assert KIND_DEFAULT_SITE["straggler"] == "step"
    assert KIND_DEFAULT_SITE["partial_ckpt"] == "peer_snapshot"
    # the default-site table covers every kind — a new kind without a site
    # would silently never fire
    assert set(KIND_DEFAULT_SITE) == set(FAULT_KINDS)
    assert issubclass(RankLostError, RuntimeError)


def test_goodput_state_dict_roundtrip():
    t = GoodputTracker()
    for _ in range(5):
        t.record_step()
    t.record_nan_skip(2)
    t.record_restart(steps_recomputed=3, time_lost_s=1.5)
    t.record_preemption()
    sd = t.state_dict()
    assert sd["steps"] == 5 and sd["preemptions"] == 1
    assert "started_at" not in sd  # per-incarnation on purpose

    fresh = GoodputTracker()
    fresh.load_state_dict(sd)
    assert fresh.state_dict() == sd
    # partial dicts (older checkpoints) load what they have, keep the rest
    partial = GoodputTracker()
    partial.load_state_dict({"steps": 7})
    assert partial.steps == 7 and partial.restarts == 0


def test_goodput_counters_persist_through_save_load(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    acc.goodput.record_nan_skip(3)
    acc.goodput.record_restart(steps_recomputed=2)
    ckpt = acc.save_state(train_state=state)

    acc.goodput.load_state_dict({k: 0 for k in acc.goodput.state_dict()})
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    acc.load_state(ckpt, train_state=template)
    assert acc.goodput.nan_skips == 3
    assert acc.goodput.restarts == 1
    assert acc.goodput.steps_recomputed == 2


def test_host_snapshot_roundtrip_and_schema_gate(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    state, _ = step(state, next(iter(dl)))
    snap = capture_host_snapshot(state, step=1)
    assert snap.verify()
    assert snap.nbytes == snapshot_schema(state)["snapshot_bytes"]
    # the accounting model predicts exactly what capture measures
    assert peer_ckpt_accounting(state)["snapshot_bytes"] == snap.nbytes

    restored = restore_host_snapshot(snap, state)
    assert _bytes_of(restored.params["a"]) == _bytes_of(state.params["a"])
    assert _bytes_of(jax.random.key_data(restored.rng)) == _bytes_of(
        jax.random.key_data(state.rng))

    other = acc.create_train_state({"a": jnp.zeros((3,))}, optax.sgd(0.1))
    with pytest.raises(PeerSchemaError):
        check_snapshot_schemas(snapshot_schema(state), snapshot_schema(other))


def test_peer_snapshotter_crc_gate_and_recover_single_process(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    snapper = PeerSnapshotter(state, 1)
    state, _ = step(state, next(iter(dl)))
    snapper.maybe_snapshot(state, 1)
    assert snapper.newest_restorable_step() == 1
    # the prepared step donates its input: read wave-1's expectation NOW,
    # before state's buffers are reused in place by the next step
    want = _bytes_of(state.params["a"])

    # torn wave: the injected partial_ckpt flips a stored byte — verify()
    # catches it and recover() skips the wave (at=1: the occurrence counter
    # is per-plan, and this plan sees only the second snapshot)
    install_fault_plan(FaultPlan([FaultEvent("partial_ckpt", at=1)]))
    state2, _ = step(state, next(iter(dl)))
    snapper.maybe_snapshot(state2, 2)
    got, agreed = snapper.recover(state2)
    assert agreed == 1  # wave 2 dropped by the crc gate
    assert _bytes_of(got.params["a"]) == want


def test_accelerator_recover_ladder_single_process(tmp_path):
    """The three rungs in order: peer RAM (newest, fewest steps replayed),
    verified disk, fresh start — with the report the bench surface emits."""
    plugin = ResiliencePlugin(peer_snapshot_every=2)
    acc, dl, state, step = _setup(tmp_path, plugin=plugin)
    it = iter(dl)
    for i in range(3):
        state, _ = step(state, next(it))
        if acc.step_count == 1:
            acc.save_state(train_state=state)        # disk @ step 1
    assert acc.peer_snapshotter.newest_restorable_step() == 2

    restored, report = acc.recover(train_state=state, load_sampler_states=False)
    assert report["restore_path"] == "peer"
    assert report["restored_step"] == 2 and acc.step_count == 2
    assert report["peer_snapshot_bytes"] > 0

    acc.peer_snapshotter.forget_local()              # rank-local RAM gone
    restored, report = acc.recover(train_state=state, load_sampler_states=False)
    assert report["restore_path"] == "disk"
    assert report["restored_step"] == 1 and acc.step_count == 1
    assert report["steps_recomputed"] == 1           # step 2 replayed

    acc.peer_snapshotter.reset()
    import shutil
    shutil.rmtree(Path(tmp_path) / "checkpoints")
    restored, report = acc.recover(train_state=state, load_sampler_states=False)
    assert report["restore_path"] == "fresh"
    assert restored is None and acc.step_count == 0
    # peer rung counted a restart; the disk rung RESTORED the persisted
    # counters (saved with restarts=0) before counting its own; fresh adds 1
    assert acc.goodput.restarts == 2
