"""HF-format checkpoint interop (models/hf_interop.py): golden logits
parity against ``transformers.LlamaForCausalLM`` — the strongest possible
guarantee that a reference user's Llama checkpoints load correctly (name
remap, [out, in] -> [in, out] kernel transpose, rotary convention)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.models.hf_interop import (
    hf_llama_key_map,
    hf_llama_tensor_map,
    load_hf_llama,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")
safetensors_torch = pytest.importorskip("safetensors.torch")


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    """A tiny random HF Llama and its safetensors checkpoint on disk."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attention_bias=False,
    )
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    path = tmp_path_factory.mktemp("hf_ckpt") / "model.safetensors"
    safetensors_torch.save_file(
        {k: v.contiguous() for k, v in hf_model.state_dict().items()}, str(path)
    )
    return hf_model, path


def test_key_map_covers_hf_llama_names(hf_checkpoint):
    hf_model, _ = hf_checkpoint
    for name in hf_model.state_dict():
        mapped = hf_llama_key_map(name)
        assert mapped is None or mapped.startswith("params."), (name, mapped)
        if "proj" in name:
            assert mapped.endswith(".kernel"), (name, mapped)


def test_hf_llama_logits_parity(hf_checkpoint):
    hf_model, path = hf_checkpoint
    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, dtype=jnp.float32,
    )
    model = LlamaForCausalLM(cfg)
    params, _ = load_hf_llama(model, path, dtype=jnp.float32)

    ids = np.random.default_rng(0).integers(0, 256, (2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


@pytest.fixture(scope="module")
def hf_t5_checkpoint(tmp_path_factory):
    """A tiny random HF T5 (v1.1 layout: gated-gelu, untied head) and its
    safetensors checkpoint on disk."""
    hf_cfg = transformers.T5Config(
        vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=32,
        feed_forward_proj="gated-gelu", tie_word_embeddings=False,
        decoder_start_token_id=0,
    )
    torch.manual_seed(1)
    hf_model = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    path = tmp_path_factory.mktemp("hf_t5_ckpt") / "model.safetensors"
    sd = {
        k: v.contiguous()
        for k, v in hf_model.state_dict().items()
        # real T5 exports store `shared.weight` once, not its two aliases
        if not k.endswith("embed_tokens.weight")
    }
    safetensors_torch.save_file(sd, str(path))
    return hf_model, path


@pytest.mark.slow
def test_hf_t5_key_map_covers_names(hf_t5_checkpoint):
    from accelerate_tpu.models.hf_interop import hf_t5_key_map

    hf_model, _ = hf_t5_checkpoint
    for name in hf_model.state_dict():
        mapped = hf_t5_key_map(name)
        assert mapped is None or mapped.startswith("params."), (name, mapped)


@pytest.mark.slow
def test_hf_t5_logits_parity(hf_t5_checkpoint):
    """Golden parity vs transformers.T5ForConditionalGeneration: encoder,
    decoder, cross attention, relative-position bias, untied head."""
    from accelerate_tpu.models import T5Config, T5ForConditionalGeneration
    from accelerate_tpu.models.hf_interop import load_hf_t5

    hf_model, path = hf_t5_checkpoint
    cfg = T5Config(
        vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_decoder_layers=2, num_heads=4, relative_attention_num_buckets=32,
        tie_word_embeddings=False, dtype=jnp.float32,
    )
    model = T5ForConditionalGeneration(cfg)
    params, _ = load_hf_t5(model, path, dtype=jnp.float32)

    rng = np.random.default_rng(1)
    enc_ids = rng.integers(0, 256, (2, 10))
    dec_ids = rng.integers(0, 256, (2, 6))
    ours = np.asarray(
        model.apply(params, jnp.asarray(enc_ids, jnp.int32), jnp.asarray(dec_ids, jnp.int32))
    )
    with torch.no_grad():
        theirs = hf_model(
            input_ids=torch.from_numpy(enc_ids),
            decoder_input_ids=torch.from_numpy(dec_ids),
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_hf_t5_ungated_checkpoint_targeted_error():
    from accelerate_tpu.models.hf_interop import hf_t5_key_map

    with pytest.raises(ValueError, match="ungated"):
        hf_t5_key_map("encoder.block.0.layer.1.DenseReluDense.wi.weight")


@pytest.fixture(scope="module")
def hf_bert_checkpoint(tmp_path_factory):
    """A tiny random HF BERT sequence classifier and its checkpoint."""
    hf_cfg = transformers.BertConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, type_vocab_size=2, num_labels=2,
        hidden_act="gelu", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(2)
    hf_model = transformers.BertForSequenceClassification(hf_cfg).eval()
    path = tmp_path_factory.mktemp("hf_bert_ckpt") / "model.safetensors"
    safetensors_torch.save_file(
        {k: v.contiguous() for k, v in hf_model.state_dict().items()}, str(path)
    )
    return hf_model, path


@pytest.mark.slow
def test_hf_bert_logits_parity(hf_bert_checkpoint):
    """Golden parity vs transformers.BertForSequenceClassification —
    including the token-type-embedding fold into positions."""
    from accelerate_tpu.models import BertConfig, BertForSequenceClassification
    from accelerate_tpu.models.hf_interop import load_hf_bert

    hf_model, path = hf_bert_checkpoint
    cfg = BertConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, num_labels=2, dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    params, _ = load_hf_bert(model, path, dtype=jnp.float32)

    rng = np.random.default_rng(2)
    ids = rng.integers(0, 512, (2, 12))
    mask = np.ones_like(ids)
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32), jnp.asarray(mask, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(
            input_ids=torch.from_numpy(ids), attention_mask=torch.from_numpy(mask)
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_tensor_map_transposes_kernels_only():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    assert hf_llama_tensor_map("params/x/kernel", a).shape == (3, 2)
    assert hf_llama_tensor_map("params/embed_tokens/embedding", a).shape == (2, 3)
    assert hf_llama_tensor_map("params/norm/scale", a[0]).shape == (3,)


def test_load_hf_llama_scan_layers_guard(hf_checkpoint):
    _, path = hf_checkpoint
    cfg = LlamaConfig.tiny(scan_layers=True)
    with pytest.raises(ValueError, match="stack_layer_params"):
        load_hf_llama(LlamaForCausalLM(cfg), path)


@pytest.fixture(scope="module")
def hf_mixtral_checkpoint(tmp_path_factory):
    hf_cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    hf_model = transformers.MixtralForCausalLM(hf_cfg).eval()
    path = tmp_path_factory.mktemp("hf_mixtral") / "model.safetensors"
    safetensors_torch.save_file(
        {k: v.contiguous() for k, v in hf_model.state_dict().items()}, str(path)
    )
    return hf_model, path


@pytest.mark.slow
def test_hf_mixtral_logits_parity(hf_mixtral_checkpoint):
    """Expert stacking pass: per-expert w1/w2/w3 land transposed in the
    stacked [E, d, f] arrays; logits match transformers' Mixtral (capacity
    set high enough that the GShard dispatch drops no tokens, matching
    HF's drop-free routing)."""
    from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM
    from accelerate_tpu.models.hf_interop import load_hf_mixtral

    hf_model, path = hf_mixtral_checkpoint
    cfg = MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2, capacity_factor=8.0,
        max_position_embeddings=128, dtype=jnp.float32,
    )
    model = MixtralForCausalLM(cfg)
    params, _ = load_hf_mixtral(model, path, dtype=jnp.float32)

    ids = np.random.default_rng(1).integers(0, 256, (2, 12))
    ours = np.asarray(model.apply(params, jnp.asarray(ids, jnp.int32)))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_hf_mixtral_sharded_load(hf_mixtral_checkpoint):
    """With a mesh, the stacked expert tensors land in their PLANNED shards
    like every other weight (the stream adapter feeds the normal loader —
    r3 review finding: a bolt-on second pass bypassed the sharding plan)."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import MixtralConfig, MixtralForCausalLM
    from accelerate_tpu.models.hf_interop import load_hf_mixtral
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    _, path = hf_mixtral_checkpoint
    cfg = MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, dtype=jnp.float32,
    )
    params, _ = load_hf_mixtral(MixtralForCausalLM(cfg), path, mesh=acc.mesh)
    leaf = params["params"]["layers_0"]["block_sparse_moe"]["experts"]["gate_proj"]
    assert leaf.shape == (4, 64, 128)
    assert hasattr(leaf.sharding, "mesh")  # NamedSharding from the plan
