"""bf16-master lion with stochastic rounding (ops/stochastic_rounding.py) —
the 7B host-offload traffic lever (docs/performance.md).  Pins: the round is
unbiased, survives sub-ulp updates that nearest-even kills, reconstructs
bit-exactly through optax.apply_updates, and tracks fp32-master lion."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu.ops.stochastic_rounding import (
    lion_bf16_sr,
    stochastic_round_to_bf16,
)


def test_sr_is_unbiased_and_bounded():
    """E[SR(x)] = x; every sample is one of the two neighboring bf16s."""
    x = jnp.float32(1.0 + 1.0 / 512.0)  # sits strictly between bf16 neighbors
    lo, hi = jnp.bfloat16(1.0), jnp.bfloat16(1.0078125)
    keys = jax.random.split(jax.random.key(0), 4096)
    samples = jax.vmap(lambda k: stochastic_round_to_bf16(x, k))(keys)
    vals = np.asarray(samples, np.float32)
    assert set(np.unique(vals)) <= {float(lo), float(hi)}
    # fractional position of x in [lo, hi] is the expected P(hi)
    frac = (float(x) - float(lo)) / (float(hi) - float(lo))
    p_hi = float((vals == float(hi)).mean())
    assert abs(p_hi - frac) < 0.03, (p_hi, frac)
    mean = float(vals.mean())
    assert abs(mean - float(x)) < 2e-4, (mean, float(x))


def test_sr_exact_values_pass_through():
    """Values already representable in bf16 never move.  (Every entry must
    BE bf16-exact: 1e-3 is not — it sits strictly between bf16 neighbors,
    so SR may legitimately round it up on some RNG streams; 2^-10 is.)"""
    xs = jnp.float32(np.array([0.0, 1.0, -2.5, 384.0, 2.0 ** -10]))
    for i in range(8):
        out = stochastic_round_to_bf16(xs, jax.random.key(i))
        np.testing.assert_array_equal(
            np.asarray(out, np.float32), np.asarray(xs.astype(jnp.bfloat16), np.float32)
        )


def test_hashed_sr_is_unbiased_over_salts():
    """The host-region-safe hashed variant: over many salts, E[SR(x)] = x
    and P(up) equals the fractional position."""
    from accelerate_tpu.ops.stochastic_rounding import stochastic_round_to_bf16_hashed

    x = jnp.float32(1.0 + 1.0 / 512.0)
    lo, hi = 1.0, 1.0078125
    salts = jnp.arange(4096, dtype=jnp.uint32) * jnp.uint32(0x9E3779B1)
    samples = jax.vmap(lambda s: stochastic_round_to_bf16_hashed(x, s))(salts)
    vals = np.asarray(samples, np.float32).reshape(-1)
    assert set(np.unique(vals)) <= {lo, hi}
    frac = (float(x) - lo) / (hi - lo)
    p_hi = float((vals == hi).mean())
    assert abs(p_hi - frac) < 0.03, (p_hi, frac)


def test_sub_ulp_updates_survive_on_average():
    """lr far below the weight's bf16 ulp: nearest-even would freeze the
    weight forever; SR moves it by the right amount in expectation.  Grads
    vary per lane (the entropy channel) as in any real training step."""
    w = jnp.full((4096,), 1.0, jnp.bfloat16)  # ulp(1.0) = 1/128 in bf16
    lr = 1e-4  # ~77x below half-ulp
    tx = lion_bf16_sr(learning_rate=lr, b1=0.9, b2=0.99)
    params = {"w": w}
    state = tx.init(params)
    rng = np.random.default_rng(0)
    for _ in range(100):
        # positive, per-lane-distinct gradients: sign(update) stays +1
        g = {"w": jnp.asarray(rng.uniform(0.5, 1.5, (4096,)).astype(np.float32))}
        updates, state = tx.update(g, state, params)
        params = optax.apply_updates(params, updates)
    drift = 1.0 - float(np.asarray(params["w"], np.float32).mean())
    # expected drift after 100 steps of -lr: 0.01; SR noise averages out
    # across 4096 lanes
    assert 0.007 < drift < 0.013, drift


def test_apply_updates_reconstructs_bitwise():
    """The fp32 delta through optax.apply_updates lands exactly on the
    stochastically rounded weight (no second rounding)."""
    key = jax.random.key(3)
    p = {"w": jax.random.normal(key, (512,), jnp.float32).astype(jnp.bfloat16)}
    g = {"w": jax.random.normal(jax.random.fold_in(key, 1), (512,), jnp.float32)}
    tx = lion_bf16_sr(learning_rate=3e-3)
    state = tx.init(p)
    updates, state = tx.update(g, state, p)
    applied = optax.apply_updates(p, updates)
    # reconstruct what update() rounded to, independently
    expect = np.asarray(p["w"], np.float32) + np.asarray(updates["w"], np.float32)
    np.testing.assert_array_equal(
        np.asarray(applied["w"], np.float32), expect.astype(jnp.bfloat16).astype(np.float32)
    )
    assert applied["w"].dtype == jnp.bfloat16


def test_sr_lion_tracks_fp32_master_lion():
    """Convergence parity on a regression: bf16-SR masters reach the same
    loss neighborhood as fp32 masters under plain lion."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    w_true = rng.normal(size=(16,)).astype(np.float32)
    y = x @ w_true

    def loss_fn(p):
        return jnp.mean((jnp.asarray(x) @ p["w"].astype(jnp.float32) - jnp.asarray(y)) ** 2)

    def train(tx, w0):
        params = {"w": w0}
        state = tx.init(params)
        for _ in range(400):
            grads = jax.grad(loss_fn)(params)
            grads = {"w": grads["w"].astype(jnp.float32)}
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return float(loss_fn(params))

    base = train(optax.lion(3e-3, b1=0.9, b2=0.99, mu_dtype=jnp.bfloat16),
                 jnp.zeros((16,), jnp.float32))
    sr = train(lion_bf16_sr(3e-3, b1=0.9, b2=0.99), jnp.zeros((16,), jnp.bfloat16))
    # same optimizer, quarter the master precision: within a small factor
    assert sr < max(4 * base, 5e-3), (sr, base)


def test_update_requires_params():
    tx = lion_bf16_sr()
    state = tx.init({"w": jnp.zeros((4,), jnp.bfloat16)})
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.zeros((4,), jnp.bfloat16)}, state)


@pytest.mark.slow
def test_adamw_sr_nu_tracks_where_nearest_freezes():
    """The adamw-specific motivation: with b2=0.999 the nu increment
    (1-b2)(g²-v) is ~0.1% relative — below the bf16 half-ulp (~0.2-0.4%) —
    so a nearest-even bf16 nu stalls far from its fixed point E[g²], while
    the SR nu reaches it in expectation."""
    from accelerate_tpu.ops.stochastic_rounding import adamw_bf16_sr

    rng = np.random.default_rng(0)
    n, steps, b2 = 2048, 3000, 0.999
    gs = rng.uniform(0.9, 1.1, (steps, n)).astype(np.float32)
    eg2 = float((gs**2).mean())
    target = eg2 * (1.0 - b2**steps)  # fp32 EMA of g² from zero

    # what a naive bf16-nearest second moment does: freezes around v ~ g²/5
    v_near = np.zeros((n,), np.float32)
    for t in range(steps):
        v_near = np.asarray(
            jnp.asarray(b2 * v_near + (1 - b2) * gs[t] ** 2).astype(jnp.bfloat16),
            np.float32,
        )
    assert v_near.mean() < 0.5 * target, (v_near.mean(), target)

    tx = adamw_bf16_sr(learning_rate=0.0, b1=0.9, b2=b2)  # lr 0: isolate nu
    params = {"w": jnp.ones((n,), jnp.bfloat16)}
    state = tx.init(params)
    for t in range(steps):
        _, state = tx.update({"w": jnp.asarray(gs[t])}, state, params)
    v_sr = float(np.asarray(state.nu["w"], np.float32).mean())
    assert abs(v_sr - target) < 0.1 * target, (v_sr, target, float(v_near.mean()))


def test_adamw_sr_tracks_fp32_adamw():
    """Convergence parity on a regression: bf16 params + bf16 SR moments
    reach the same loss neighborhood as stock fp32 adamw."""
    from accelerate_tpu.ops.stochastic_rounding import adamw_bf16_sr

    rng = np.random.default_rng(1)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    w_true = rng.normal(size=(16,)).astype(np.float32)
    y = x @ w_true

    def loss_fn(p):
        return jnp.mean((jnp.asarray(x) @ p["w"].astype(jnp.float32) - jnp.asarray(y)) ** 2)

    def train(tx, w0):
        params = {"w": w0}
        state = tx.init(params)
        for _ in range(400):
            grads = {"w": jax.grad(loss_fn)(params)["w"].astype(jnp.float32)}
            updates, state = tx.update(grads, state, params)
            params = optax.apply_updates(params, updates)
        return float(loss_fn(params))

    base = train(optax.adamw(3e-2), jnp.zeros((16,), jnp.float32))
    sr = train(adamw_bf16_sr(3e-2), jnp.zeros((16,), jnp.bfloat16))
    assert sr < max(4 * base, 5e-3), (sr, base)


def test_adamw_sr_apply_updates_reconstructs_bitwise():
    """Same optax delta contract as lion_bf16_sr: the fp32 delta through
    apply_updates lands exactly on the stochastically rounded weight."""
    from accelerate_tpu.ops.stochastic_rounding import adamw_bf16_sr

    key = jax.random.key(7)
    p = {"w": jax.random.normal(key, (512,), jnp.float32).astype(jnp.bfloat16)}
    g = {"w": jax.random.normal(jax.random.fold_in(key, 1), (512,), jnp.float32)}
    tx = adamw_bf16_sr(learning_rate=3e-3)
    state = tx.update(g, tx.init(p), p)[1]
    updates, state = tx.update(g, state, p)
    applied = optax.apply_updates(p, updates)
    expect = np.asarray(p["w"], np.float32) + np.asarray(updates["w"], np.float32)
    np.testing.assert_array_equal(
        np.asarray(applied["w"], np.float32), expect.astype(jnp.bfloat16).astype(np.float32)
    )
    assert applied["w"].dtype == jnp.bfloat16
    assert state.mu["w"].dtype == jnp.bfloat16
    assert state.nu["w"].dtype == jnp.bfloat16


def test_adamw_sr_update_requires_params():
    from accelerate_tpu.ops.stochastic_rounding import adamw_bf16_sr

    tx = adamw_bf16_sr()
    state = tx.init({"w": jnp.zeros((4,), jnp.bfloat16)})
    with pytest.raises(ValueError, match="params"):
        tx.update({"w": jnp.zeros((4,), jnp.bfloat16)}, state)
