"""Serving-core tests: paged KV cache, block allocator, continuous-batching
scheduler, and the acceptance pin — the paged serving path emits tokens
IDENTICAL to ``generate()`` for the same requests (ISSUE 6 / ROADMAP item 1;
reference capability role: production-scale big-model inference,
big_modeling.py:513)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate, generate_paged
from accelerate_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    init_paged_cache,
    paged_gather_kv,
    cached_attention,
)
from accelerate_tpu.serving import (
    Request,
    ServingEngine,
    allocate,
    kv_pool_accounting,
    pages_for,
    release,
    replay,
    static_batching_report,
    synthesize_trace,
)
from accelerate_tpu.utils.dataclasses import ServingPlugin


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _plugin(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("decode_kernel", "native")
    return ServingPlugin(**kw)


def _ref_tokens(model, params, prompt, n, **cfg_kw):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   GenerationConfig(max_new_tokens=n, **cfg_kw))
    return [int(x) for x in out[0]]


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------


def test_allocator_alloc_release_roundtrip():
    """Pages popped for a batch of slots are unique; releasing the slots
    pushes exactly those pages back and restores the free count."""
    num_pages, n_slots, n_cols, page = 16, 4, 4, 4
    bt = jnp.zeros((n_slots, n_cols), jnp.int32)
    stack = jnp.arange(num_pages, dtype=jnp.int32)
    top = jnp.asarray(num_pages, jnp.int32)

    # slot i allocates its page 0 (4 pops at once)
    need = jnp.ones((n_slots,), bool)
    bt, top = allocate(bt, stack, top, jnp.arange(n_slots), jnp.zeros((n_slots,), jnp.int32), need)
    assert int(top) == num_pages - n_slots
    got = np.asarray(bt[:, 0])
    assert len(set(got.tolist())) == n_slots  # all distinct physical pages

    # write 3 tokens into each slot, then release slots 1 and 3
    seq_lens = jnp.full((n_slots,), 3, jnp.int32)
    mask = jnp.asarray([False, True, False, True])
    seq_lens, stack, top2 = release(bt, seq_lens, stack, top, mask, page)
    assert int(top2) == int(top) + 2
    assert np.asarray(seq_lens).tolist() == [3, 0, 3, 0]
    # the returned pages are the released slots' page-0 entries
    returned = set(np.asarray(stack)[int(top): int(top2)].tolist())
    assert returned == {int(got[1]), int(got[3])}

    # masked-out lanes never allocate: need=False drops the scatter
    bt2, top3 = allocate(bt, stack, top2, jnp.arange(n_slots),
                         jnp.ones((n_slots,), jnp.int32), jnp.zeros((n_slots,), bool))
    assert int(top3) == int(top2)
    np.testing.assert_array_equal(np.asarray(bt2), np.asarray(bt))


def test_pages_for_and_pool_accounting():
    assert [int(pages_for(t, 4)) for t in (0, 1, 4, 5, 8)] == [0, 1, 1, 2, 2]
    cfg = LlamaConfig.tiny()
    acct = kv_pool_accounting(cfg, num_pages=64, page_size=16, dtype_bytes=2)
    # 2 (K+V) * L * page * Hkv * D * bytes
    assert acct["bytes_per_page"] == 2 * cfg.num_hidden_layers * 16 * \
        cfg.num_key_value_heads * cfg.head_dim * 2
    assert acct["pool_bytes"] == acct["bytes_per_page"] * 64
    assert acct["tokens_capacity"] == 64 * 16
    assert 0 < acct["hbm_frac"]["v5e_16GiB"] < 1


# ---------------------------------------------------------------------------
# paged attention parity (model level + kernel level)
# ---------------------------------------------------------------------------


def test_paged_prefill_decode_matches_full_forward(tiny_model):
    """Prefill + per-token decode through the paged cache reproduce the
    uncached forward bitwise (the paged analog of the dense-cache
    invariant)."""
    model, params = tiny_model
    ids = jnp.asarray([[3, 17, 99, 4, 250, 7, 12, 63]], jnp.int32)
    full = model.apply(params, ids)

    page_size, slots, pps = 4, 1, 4
    pc = init_paged_cache(model.config, 8, page_size, slots, pps)
    bt = jnp.arange(slots * pps, dtype=jnp.int32).reshape(slots, pps)
    layers = [{"k_pages": l["k_pages"], "v_pages": l["v_pages"], "block_tables": bt}
              for l in pc["layers"]]
    lg, layers = model.apply(
        params, ids[:, :5], positions=jnp.arange(5)[None],
        cache=layers, cache_write_mask=jnp.ones((1, 5), bool),
    )
    np.testing.assert_array_equal(np.asarray(lg), np.asarray(full[:, :5]))
    for t in range(5, 8):
        layers = [{**l, "block_tables": bt} for l in layers]
        lg, layers = model.apply(
            params, ids[:, t:t + 1], positions=jnp.asarray([[t]]),
            cache=layers, cache_write_mask=jnp.ones((1, 1), bool),
        )
        np.testing.assert_array_equal(np.asarray(lg[:, 0]), np.asarray(full[:, t]),
                                      err_msg=f"step {t}")


def test_paged_flash_decode_matches_gather_reference():
    """The Pallas paged-decode kernel == gather-through-the-block-table +
    dense cached attention, on ragged positions incl. a dead slot."""
    from accelerate_tpu.ops.flash_attention import paged_decode_attention

    rng = np.random.default_rng(0)
    hkv, num_pages, page, d, slots, n, h = 2, 16, 8, 32, 4, 4, 4
    kp = jnp.asarray(rng.normal(size=(hkv, num_pages, page, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(hkv, num_pages, page, d)), jnp.float32)
    bt = jnp.asarray(rng.permutation(num_pages)[: slots * n].reshape(slots, n), jnp.int32)
    pos = jnp.asarray([0, 5, 17, 31], jnp.int32)
    q = jnp.asarray(rng.normal(size=(slots, h, d)), jnp.float32)
    out = paged_decode_attention(q, kp, vp, bt, pos)
    k_lin, v_lin, kvpos = paged_gather_kv(kp, vp, bt)
    ref = cached_attention(q[:, None], k_lin, v_lin, kvpos, pos[:, None])[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def _quantized_pool(rng, hkv, num_pages, page, d):
    """Emulate the write path's per-(head, page) int8 quantization."""
    f = rng.normal(size=(hkv, num_pages, page, d)).astype(np.float32)
    amax = np.abs(f).max(axis=(2, 3))                        # [Hkv, P]
    codes = np.rint(f * (127.0 / amax[:, :, None, None]))
    return (jnp.asarray(np.clip(codes, -127, 127), jnp.int8),
            jnp.asarray(amax, jnp.float32))


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_paged_multitoken_matches_cached_reference(kv_dtype):
    """Interpret-mode parity for the multi-token Pallas kernel at the
    speculative-verify width T = k+1, ragged positions, dense AND
    quantized pools: kernel == gather(+dequant) + dense cached attention
    with per-row causal masking."""
    from accelerate_tpu.ops.flash_attention import paged_multitoken_attention

    rng = np.random.default_rng(0)
    hkv, num_pages, page, d, slots, n, h, width = 2, 16, 8, 32, 4, 4, 4, 4
    if kv_dtype:
        kp, ks = _quantized_pool(rng, hkv, num_pages, page, d)
        vp, vs = _quantized_pool(rng, hkv, num_pages, page, d)
    else:
        kp = jnp.asarray(rng.normal(size=(hkv, num_pages, page, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(hkv, num_pages, page, d)), jnp.float32)
        ks = vs = None
    bt = jnp.asarray(rng.permutation(num_pages)[: slots * n].reshape(slots, n), jnp.int32)
    # per-slot verify windows starting at ragged depths (last one ends at
    # the pool's final token, exercising the page-skip predicate edge)
    pos = jnp.asarray([0, 5, 17, 28], jnp.int32)[:, None] + jnp.arange(width)[None]
    q = jnp.asarray(rng.normal(size=(slots, width, h, d)), jnp.float32)
    out = paged_multitoken_attention(q, kp, vp, bt, pos, k_scales=ks, v_scales=vs)
    k_lin, v_lin, kvpos = paged_gather_kv(kp, vp, bt, ks, vs, kv_dtype, jnp.float32)
    ref = cached_attention(q, k_lin, v_lin, kvpos, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_fused_bgmv_paged_decode_matches_composed_reference(kv_dtype):
    """The consolidated LoRA-query + paged-decode kernel == the two-trip
    composition it replaces: bgmv adapter delta, roped at the slot's
    position, added to the pre-roped base query, then paged decode."""
    from accelerate_tpu.models.llama import apply_rope, rope_frequencies
    from accelerate_tpu.ops.flash_attention import (
        fused_bgmv_paged_decode,
        paged_decode_attention,
    )

    rng = np.random.default_rng(1)
    hkv, num_pages, page, d, slots, n, h = 2, 16, 8, 32, 4, 4, 4
    d_in, rank, n_adapters = 48, 4, 3
    if kv_dtype:
        kp, ks = _quantized_pool(rng, hkv, num_pages, page, d)
        vp, vs = _quantized_pool(rng, hkv, num_pages, page, d)
    else:
        kp = jnp.asarray(rng.normal(size=(hkv, num_pages, page, d)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(hkv, num_pages, page, d)), jnp.float32)
        ks = vs = None
    bt = jnp.asarray(rng.permutation(num_pages)[: slots * n].reshape(slots, n), jnp.int32)
    pos = jnp.asarray([0, 5, 17, 31], jnp.int32)
    x = jnp.asarray(rng.normal(size=(slots, d_in)), jnp.float32)
    q_base = jnp.asarray(rng.normal(size=(slots, h, d)), jnp.float32)
    # AdapterStore pool layout: row 0 is the zero base slot
    a_np = rng.normal(size=(n_adapters, d_in, rank)).astype(np.float32) * 0.1
    b_np = rng.normal(size=(n_adapters, rank, h * d)).astype(np.float32) * 0.1
    a_np[0] = 0.0
    b_np[0] = 0.0
    a_stack, b_stack = jnp.asarray(a_np), jnp.asarray(b_np)
    ids = jnp.asarray([0, 1, 2, 1], jnp.int32)
    cos, sin = map(jnp.asarray, rope_frequencies(d, 64, 10000.0))

    out = fused_bgmv_paged_decode(x, q_base, a_stack, b_stack, ids, cos, sin,
                                  kp, vp, bt, pos, k_scales=ks, v_scales=vs)
    # composed reference: per-slot bgmv, rope the delta, add, paged decode
    delta = jnp.einsum("sr,srm->sm", jnp.einsum("si,sir->sr", x, a_stack[ids]),
                       b_stack[ids]).reshape(slots, h, d)
    delta = apply_rope(delta[:, None], cos, sin, pos[:, None])[:, 0]
    ref = paged_decode_attention(q_base + delta, kp, vp, bt, pos,
                                 k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


# ---------------------------------------------------------------------------
# the acceptance pin: serving tokens == generate() tokens
# ---------------------------------------------------------------------------


def test_generate_paged_matches_generate(tiny_model):
    """Same requests through generate() and the paged serving path produce
    IDENTICAL tokens (variable-length rows + EOS padding included)."""
    model, params = tiny_model
    batch = jnp.asarray([[5, 42, 7, 9], [11, 3, 0, 0]], jnp.int32)
    lens = jnp.asarray([4, 2])
    cfg = GenerationConfig(max_new_tokens=5, eos_token_id=2, pad_token_id=0)
    ref = generate(model, params, batch, cfg, prompt_lengths=lens)
    got = generate_paged(model, params, batch, cfg, prompt_lengths=lens)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_generate_paged_chunked_prefill_matches(tiny_model):
    """Chunked prefill (prompt split across engine ticks, bucket-padded)
    changes nothing about the emitted tokens."""
    model, params = tiny_model
    rng = np.random.default_rng(3)
    prompt = tuple(int(x) for x in rng.integers(1, 255, 11))
    plugin = _plugin(num_slots=2, num_pages=16, prefill_chunk=4, prefill_buckets=(4,))
    gcfg = GenerationConfig(max_new_tokens=5)
    eng = ServingEngine(model, params, plugin, gcfg)
    eng.add_request(Request(uid=0, prompt=prompt, max_new_tokens=5))
    while not eng.idle():
        eng.step()
    assert eng.results[0] == _ref_tokens(model, params, prompt, 5)
    assert eng.metrics["prefill_steps"] == 3  # 11 tokens / chunk 4
    assert eng.free_page_mirror_in_sync()


def test_paged_flash_decode_kernel_end_to_end(tiny_model):
    """decode_kernel='flash' routes decode through the Pallas paged kernel
    (interpret mode off-TPU) — tokens still match generate()."""
    model, params = tiny_model
    plugin = _plugin(num_slots=2, num_pages=16, decode_kernel="flash")
    eng = ServingEngine(model, params, plugin, GenerationConfig(max_new_tokens=4))
    eng.add_request(Request(uid=0, prompt=(5, 42, 7), max_new_tokens=4))
    while not eng.idle():
        eng.step()
    assert eng.results[0] == _ref_tokens(model, params, (5, 42, 7), 4)


# ---------------------------------------------------------------------------
# continuous batching: eviction, determinism, preemption, the static twin
# ---------------------------------------------------------------------------


def test_eviction_recompute_preserves_tokens(tiny_model):
    """A pool too small for the offered load forces preempt-and-recompute
    evictions; every request still emits exactly its solo-run tokens, and
    the host page mirror stays in sync with the device allocator."""
    model, params = tiny_model
    rng = np.random.default_rng(1)
    prompts = [tuple(int(x) for x in rng.integers(1, 255, n)) for n in (9, 7, 8)]
    plugin = ServingPlugin(num_slots=3, page_size=2, pages_per_slot=10,
                           num_pages=12, prefill_chunk=8, decode_kernel="native")
    eng = ServingEngine(model, params, plugin, GenerationConfig(max_new_tokens=8))
    for i, p in enumerate(prompts):
        eng.add_request(Request(uid=i, prompt=p, max_new_tokens=8))
    while not eng.idle():
        eng.step()
    assert eng.metrics["evictions"] > 0
    assert eng.free_page_mirror_in_sync()
    for i, p in enumerate(prompts):
        assert eng.results[i] == _ref_tokens(model, params, p, 8), f"request {i}"


def test_scheduler_determinism_under_seeded_trace(tiny_model):
    """Same seed -> same trace -> identical schedule (event-for-event) and
    identical tokens; a different seed schedules differently."""
    model, params = tiny_model
    gcfg = GenerationConfig(max_new_tokens=32)

    def run(seed):
        trace = synthesize_trace(seed, 8, vocab_size=255,
                                 prompt_len_range=(3, 10), new_tokens_range=(2, 6))
        eng = ServingEngine(model, params, _plugin(), gcfg)
        results = eng.run(trace)
        return eng.sched.events, results

    ev_a, res_a = run(7)
    ev_b, res_b = run(7)
    assert ev_a == ev_b
    assert res_a == res_b
    ev_c, _ = run(8)
    assert ev_c != ev_a


def test_preemption_mid_serve_drains_and_resumes(tiny_model):
    """A 'preempt' fault at the serve_step site (resilience/faults.py) drains
    the engine: finished results survive, every other request comes back
    intact, and a fresh engine finishing the remainder reproduces the
    uninterrupted run token-for-token."""
    from accelerate_tpu.resilience.faults import FaultEvent, FaultPlan, fault_plan

    model, params = tiny_model
    gcfg = GenerationConfig(max_new_tokens=32)
    trace = synthesize_trace(7, 8, vocab_size=255,
                             prompt_len_range=(3, 10), new_tokens_range=(2, 6))
    full = ServingEngine(model, params, _plugin(), gcfg).run(trace)

    eng = ServingEngine(model, params, _plugin(), gcfg)
    plan = FaultPlan([FaultEvent("preempt", at=9, site="serve_step")])
    with fault_plan(plan):
        partial = eng.run(trace)
    assert eng.interrupted
    assert plan.fired  # the injection actually happened
    remaining = eng.remaining_requests()
    assert set(partial) | {r.uid for r in remaining} == {r.uid for r in trace}

    resumed = ServingEngine(model, params, _plugin(), gcfg).run([
        Request(uid=r.uid, prompt=r.prompt, max_new_tokens=r.max_new_tokens)
        for r in remaining
    ])
    assert {**partial, **resumed} == full


def test_continuous_beats_static_batching(tiny_model):
    """The CPU-measurable acceptance proxy: on the bench's seeded dense
    trace, continuous batching beats fixed-batch scheduling on BOTH
    padding-waste fraction and scheduled-token efficiency."""
    model, params = tiny_model
    plugin = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                           num_pages=40, prefill_chunk=16, decode_kernel="native")
    trace = synthesize_trace(0, 16, vocab_size=255, mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 24), new_tokens_range=(4, 24))
    eng = ServingEngine(model, params, plugin, GenerationConfig(max_new_tokens=64))
    rep = replay(eng, trace)
    per_req = [(len(r.prompt), len(rep["results"][r.uid])) for r in trace]
    static = static_batching_report(per_req, plugin.num_slots)
    assert rep["padding_waste_frac"] < static["padding_waste_frac"]
    assert rep["scheduled_token_efficiency"] > static["scheduled_token_efficiency"]
    # the measured/predicted utilization twins agree to the EOS-exit error
    assert rep["kv_pool_utilization"] > 0
    assert abs(rep["kv_pool_utilization"] - rep["kv_pool_utilization_predicted"]) < 0.2
    # every report field the bench contract promises is present
    for field in ("tokens_per_sec_per_chip", "p50_token_latency_ms",
                  "p99_token_latency_ms", "kv_pool_utilization",
                  "padding_waste_frac", "scheduled_token_efficiency",
                  "scheduler_occupancy", "evictions"):
        assert field in rep, field


# ---------------------------------------------------------------------------
# plugin knobs + guards + lint
# ---------------------------------------------------------------------------


def test_serving_plugin_env_defaults(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SERVE_SLOTS", "3")
    monkeypatch.setenv("ACCELERATE_SERVE_PAGE_SIZE", "8")
    monkeypatch.setenv("ACCELERATE_SERVE_PAGES", "21")
    monkeypatch.setenv("ACCELERATE_SERVE_KERNEL", "native")
    p = ServingPlugin()
    assert (p.num_slots, p.page_size, p.num_pages, p.decode_kernel) == (3, 8, 21, "native")
    # explicit arguments always win over env
    p2 = ServingPlugin(num_slots=5)
    assert p2.num_slots == 5
    # derived defaults: bucket ladder ends at prefill_chunk
    p3 = ServingPlugin(prefill_chunk=48)
    assert p3.prefill_buckets[-1] == 48 and p3.prefill_buckets[0] == 16
    with pytest.raises(ValueError):
        ServingPlugin(decode_kernel="mystery")
    with pytest.raises(ValueError):
        ServingPlugin(num_pages=2, pages_per_slot=8)
    with pytest.raises(ValueError):
        ServingPlugin(prefill_chunk=64, prefill_buckets=(16, 32))


def test_request_capacity_guard(tiny_model):
    model, params = tiny_model
    eng = ServingEngine(model, params, _plugin(), GenerationConfig(max_new_tokens=8))
    cap = min(eng.plugin.pages_per_slot, eng.plugin.num_pages) * eng.plugin.page_size
    with pytest.raises(ValueError):
        eng.add_request(Request(uid=0, prompt=tuple(range(1, cap + 1)), max_new_tokens=8))
    with pytest.raises(ValueError):
        eng.add_request(Request(uid=1, prompt=(), max_new_tokens=4))
    with pytest.raises(ValueError):
        eng.add_request(Request(uid=2, prompt=(5, 6), max_new_tokens=0))


def test_admission_matches_submit_capacity(tiny_model):
    """A submit-accepted request is always eventually admittable: a prompt
    that exactly fills the pool's last page (pages_for(prompt) == num_pages)
    must serve, not idle-spin forever (the admit-vs-submit consistency
    regression — admission may not demand pages the pool can never have)."""
    model, params = tiny_model
    rng = np.random.default_rng(5)
    prompt = tuple(int(x) for x in rng.integers(1, 255, 17))  # 2 pages of 16, minus 15
    plugin = ServingPlugin(num_slots=1, page_size=16, pages_per_slot=2,
                           num_pages=2, prefill_chunk=32, decode_kernel="native")
    gcfg = GenerationConfig(max_new_tokens=1)
    eng = ServingEngine(model, params, plugin, gcfg)
    eng.add_request(Request(uid=0, prompt=prompt, max_new_tokens=1))
    eng.run([], max_steps=200)
    assert eng.results[0] == _ref_tokens(model, params, prompt, 1)
    # and through the offline wrapper that hit the livelock originally
    out = generate_paged(model, params, jnp.asarray([prompt], jnp.int32),
                         GenerationConfig(max_new_tokens=1))
    np.testing.assert_array_equal(
        np.asarray(out),
        np.asarray(generate(model, params, jnp.asarray([prompt], jnp.int32),
                            GenerationConfig(max_new_tokens=1))),
    )


def test_serving_decode_step_audits_donation_clean(tiny_model):
    """The satellite contract: the pool update is donation-clean — the
    graft-lint jaxpr audit of the real decode step reports no unsuppressed
    GL101/GL103/GL105 (and the AST sweep holds GL201 repo-wide)."""
    model, params = tiny_model
    eng = ServingEngine(model, params, _plugin(num_slots=2, num_pages=16),
                        GenerationConfig(max_new_tokens=4))
    rep = eng.audit_decode_step(default_memory_kind="device")
    assert not rep.unsuppressed(), rep.render()
