"""Test harness config: an 8-device virtual CPU mesh, no TPU required.

SURVEY §4 'Implication for the TPU build': unit tests run on a fake 8-device
CPU mesh via ``--xla_force_host_platform_device_count=8`` — strictly better
than the reference's subprocess-only multi-device story.  Subprocess
self-launch tests (tests/test_launch.py) still exercise the real launcher.
"""

import os

# Must run before JAX's backend initializes.  Force CPU even when a real TPU
# platform (e.g. axon tunnel) is present — unit tests always use the virtual
# 8-device mesh; bench.py exercises the real chip.  jax may already be
# imported by a sitecustomize, so env vars alone are not enough — use
# jax.config.update, which works pre-backend-init either way.
os.environ["JAX_PLATFORMS"] = os.environ.get("ACCELERATE_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
if os.environ["JAX_PLATFORMS"] == "cpu":
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:  # pragma: no cover - older jax: XLA_FLAGS above covers it
        pass

# The suite is compile-dominated (single-core host); the persistent cache
# makes every run after the first skip recompiles of unchanged programs.
# SCOPED per (jax version, harness tag, worker): concurrent jax processes
# sharing one flat /tmp dir corrupted it on this rig (documented flake) —
# utils/compile_cache.py keys the dir by toolchain + tag, and gives each
# pytest-xdist worker (or ACCELERATE_JAX_CACHE_SCOPE) a private cache.
from accelerate_tpu.utils.compile_cache import enable_scoped_compilation_cache  # noqa: E402

enable_scoped_compilation_cache("tests")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Singleton hygiene between tests (reference AccelerateTestCase.tearDown
    resets AcceleratorState, testing.py:650-661)."""
    yield
    from accelerate_tpu.ops.collective_matmul import set_collective_matmul
    from accelerate_tpu.resilience.faults import install_fault_plan
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    set_collective_matmul(None)  # clear any ambient ring-matmul override
    install_fault_plan(None)     # no fault plan may leak across tests
    from accelerate_tpu.ops.lora import set_lora_kernel

    set_lora_kernel(None)        # clear any ambient LoRA kernel override
    from accelerate_tpu.telemetry import twin_registry

    twin_registry().reset()      # no twin values may leak across tests


@pytest.fixture
def mesh8():
    import jax
    from accelerate_tpu.parallelism_config import ParallelismConfig

    cfg = ParallelismConfig(dp_shard_size=8)
    return cfg.build_device_mesh(jax.devices())
