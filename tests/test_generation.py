"""Generation tests: KV-cache decode parity with the full forward, sampling
filters, variable-length prompts, EOS handling, MoE decode (reference
capability role: big-model inference / generate — big_modeling.py:513 +
benchmarks/big_model_inference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate, sample_logits
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM, init_cache
from accelerate_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def test_cached_forward_matches_full(tiny_model):
    """Prefill + per-token decode logits == one uncached forward (the
    fundamental KV-cache invariant)."""
    model, params = tiny_model
    ids = jnp.asarray([[3, 17, 99, 4, 250, 7, 12, 63]], jnp.int32)
    full_logits = model.apply(params, ids)

    cache = init_cache(model.config, 1, ids.shape[1])
    # prefill the first 5 tokens, then decode tokens 5..7 one at a time
    pre_logits, cache = model.apply(params, ids[:, :5], cache=cache)
    np.testing.assert_allclose(
        np.asarray(pre_logits), np.asarray(full_logits[:, :5]), atol=2e-2
    )
    for t in range(5, 8):
        step_logits, cache = model.apply(
            params, ids[:, t : t + 1], positions=jnp.asarray([[t]]), cache=cache
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, t]), atol=2e-2,
            err_msg=f"step {t}",
        )


@pytest.mark.slow
def test_greedy_generate_matches_manual_argmax(tiny_model):
    """generate() greedy tokens == manually re-running the full model and
    taking argmax each step (no cache)."""
    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7]], jnp.int32)
    out = generate(model, params, prompt, GenerationConfig(max_new_tokens=4))
    seq = prompt
    expect = []
    for _ in range(4):
        logits = model.apply(params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expect.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert out.shape == (1, 4)
    assert [int(x) for x in out[0]] == expect


def test_variable_length_prompts_batch(tiny_model):
    """Right-padded prompts of different lengths decode as if each ran alone
    (padding slots positionally dead in the cache)."""
    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=3)
    p1 = jnp.asarray([[5, 42, 7, 9]], jnp.int32)
    p2 = jnp.asarray([[11, 3]], jnp.int32)
    solo1 = generate(model, params, p1, cfg)
    solo2 = generate(model, params, p2, cfg)
    batch = jnp.asarray([[5, 42, 7, 9], [11, 3, 0, 0]], jnp.int32)
    out = generate(model, params, batch, cfg, prompt_lengths=jnp.asarray([4, 2]))
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(solo1[0]))
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(solo2[0]))


def test_eos_pads_tail(tiny_model):
    """Tokens after EOS come back as pad_token_id."""
    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7]], jnp.int32)
    free = generate(model, params, prompt, GenerationConfig(max_new_tokens=5))
    eos = int(free[0, 1])  # force EOS at the second emitted token
    out = generate(
        model, params, prompt,
        GenerationConfig(max_new_tokens=5, eos_token_id=eos, pad_token_id=123),
    )
    toks = [int(x) for x in out[0]]
    assert toks[1] == eos
    assert all(t == 123 for t in toks[2:])


def test_sampling_respects_top_k():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 4.0, 5.0]])
    cfg = GenerationConfig(do_sample=True, top_k=2)
    picks = {
        int(sample_logits(logits, jax.random.PRNGKey(i), cfg)[0]) for i in range(50)
    }
    assert picks <= {4, 5}
    assert len(picks) == 2  # both survivors actually reachable


def test_sampling_top_k_larger_than_vocab_clamps():
    """transformers silently clamps top_k > V; lax.top_k would raise."""
    logits = jnp.asarray([[0.0, 1.0, 2.0]])
    cfg = GenerationConfig(do_sample=True, top_k=50)
    picks = {
        int(sample_logits(logits, jax.random.PRNGKey(i), cfg)[0]) for i in range(60)
    }
    assert picks == {0, 1, 2}


def test_sampling_respects_top_p():
    # softmax of [0,0,0,10] puts ~1.0 mass on index 3 -> top_p=0.5 keeps only it
    logits = jnp.asarray([[0.0, 0.0, 0.0, 10.0]])
    cfg = GenerationConfig(do_sample=True, top_p=0.5)
    for i in range(20):
        assert int(sample_logits(logits, jax.random.PRNGKey(i), cfg)[0]) == 3


def test_sampling_top_p_zero_is_greedy():
    """top_p=0.0 keeps the single best token (never uniform-over-masked)."""
    logits = jnp.asarray([[0.5, 3.0, 1.0, 2.0]])
    cfg = GenerationConfig(do_sample=True, top_p=0.0)
    for i in range(10):
        assert int(sample_logits(logits, jax.random.PRNGKey(i), cfg)[0]) == 1


def test_sampling_greedy_ignores_rng():
    logits = jnp.asarray([[0.3, 0.1, 2.0]])
    cfg = GenerationConfig(do_sample=False)
    assert int(sample_logits(logits, jax.random.PRNGKey(0), cfg)[0]) == 2


@pytest.mark.slow
def test_mixtral_generates():
    """MoE decode path: cache threads through the Mixtral block."""
    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out = generate(model, params, jnp.asarray([[1, 2, 3]], jnp.int32),
                   GenerationConfig(max_new_tokens=3))
    assert out.shape == (1, 3)
    assert np.asarray(out).dtype == np.int32


def test_generate_do_sample_runs(tiny_model):
    model, params = tiny_model
    out = generate(
        model, params, jnp.asarray([[5, 42, 7]], jnp.int32),
        GenerationConfig(max_new_tokens=4, do_sample=True, temperature=0.8, top_k=20),
        rng=jax.random.PRNGKey(7),
    )
    assert out.shape == (1, 4)


@pytest.mark.slow
def test_t5_generate_seq2seq_greedy_matches_manual():
    """Encoder-decoder decode: scan over the fixed decoder buffer equals a
    manual grow-the-sequence greedy loop."""
    from accelerate_tpu.generation import generate_seq2seq
    from accelerate_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    src = jnp.asarray([[9, 4, 17, 2, 0, 0]], jnp.int32)
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0]], bool)
    params = model.init(jax.random.PRNGKey(0), src, src[:, :3])

    out = generate_seq2seq(model, params, src, GenerationConfig(max_new_tokens=4),
                           attention_mask=mask)

    dec = jnp.zeros((1, 1), jnp.int32)  # decoder_start_token_id = 0
    expect = []
    for _ in range(4):
        logits = model.apply(params, src, dec, mask)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expect.append(int(nxt[0]))
        dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
    assert [int(x) for x in out[0]] == expect


@pytest.mark.slow
def test_t5_encode_only_and_cached_decode():
    """encoder_output round-trip: decode with cached states == joint call."""
    from accelerate_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    src = jnp.asarray([[9, 4, 17, 2]], jnp.int32)
    dec = jnp.asarray([[0, 7, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src, dec)
    joint = model.apply(params, src, dec)
    enc = model.apply(params, src, None)
    split = model.apply(params, None, dec, encoder_output=enc)
    np.testing.assert_allclose(np.asarray(split), np.asarray(joint), atol=1e-5)


def test_beam_search_k1_equals_greedy(tiny_model):
    from accelerate_tpu.generation import beam_search

    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7]], jnp.int32)
    cfg = GenerationConfig(max_new_tokens=4)
    greedy = generate(model, params, prompt, cfg)
    beam1 = beam_search(model, params, prompt, cfg, num_beams=1)
    np.testing.assert_array_equal(np.asarray(beam1), np.asarray(greedy))


@pytest.mark.slow
def test_beam_search_score_at_least_greedy(tiny_model):
    """The best of K beams scores >= the greedy hypothesis (sum of token
    log-probs under the model)."""
    from accelerate_tpu.generation import beam_search

    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7, 9]], jnp.int32)
    cfg = GenerationConfig(max_new_tokens=5)

    def seq_logprob(new_tokens):
        seq = jnp.concatenate([prompt, new_tokens[None]], axis=1)
        logits = model.apply(params, seq)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = 0.0
        for i, tok in enumerate(np.asarray(new_tokens)):
            total += float(logp[0, prompt.shape[1] - 1 + i, int(tok)])
        return total

    greedy = generate(model, params, prompt, cfg)[0]
    beam = beam_search(model, params, prompt, cfg, num_beams=4)[0]
    assert seq_logprob(beam) >= seq_logprob(greedy) - 1e-4


def test_beam_search_length_penalty_counts_eos_step(tiny_model):
    """GNMT normalization parity (ADVICE r1): a hypothesis ending in EOS at
    step 2 has gen_len 2 (the EOS step counts), not 1.  The stub transition
    is built so the correct normalization picks the EOS beam and the
    off-by-one normalization flips to the other beam."""
    from accelerate_tpu.generation import beam_search

    model, params = tiny_model

    # vocab 4, pad=0, eos=3.  Prompt step: p = [.25, .30, .28, .17] so the
    # two live beams after step 1 hold tokens 1 (score log .30) and 2
    # (log .28).  Decode: token 1 -> EOS almost surely; token 2 -> token 2.
    # Final raw scores: A ~= log .30, B ~= log .28, both over 2 generated
    # tokens.  Correct: A/2 > B/2 -> A wins.  If the EOS step were dropped
    # from gen_len, A/1 < B/2 -> B would win.
    prefill_row = jnp.log(jnp.asarray([0.25, 0.30, 0.28, 0.17]))
    row_eos = jnp.log(jnp.asarray([0.001, 0.001, 0.001, 0.997]))
    row_tok2 = jnp.log(jnp.asarray([0.001, 0.001, 0.997, 0.001]))

    def stub_apply(params, ids, positions=None, cache=None, cache_write_mask=None):
        b, t = ids.shape
        if t > 1:  # prefill
            logits = jnp.broadcast_to(prefill_row, (b, t, 4))
        else:
            logits = jnp.where((ids == 1)[..., None], row_eos, row_tok2)
        return logits, cache

    cfg = GenerationConfig(max_new_tokens=2, eos_token_id=3, pad_token_id=0)
    out = beam_search(model, params, jnp.asarray([[5, 5]], jnp.int32), cfg,
                      num_beams=2, length_penalty=1.0, apply_fn=stub_apply)
    np.testing.assert_array_equal(np.asarray(out), [[1, 3]])


@pytest.mark.slow
def test_beam_search_batch_and_lengths(tiny_model):
    """Beam search handles right-padded variable-length prompts per row."""
    from accelerate_tpu.generation import beam_search

    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=3)
    batch = jnp.asarray([[5, 42, 7, 9], [11, 3, 0, 0]], jnp.int32)
    out = beam_search(model, params, batch, cfg, num_beams=3,
                      prompt_lengths=jnp.asarray([4, 2]))
    solo = beam_search(model, params, jnp.asarray([[11, 3]], jnp.int32), cfg, num_beams=3)
    assert out.shape == (2, 3)
    np.testing.assert_array_equal(np.asarray(out[1]), np.asarray(solo[0]))


@pytest.mark.slow
def test_generate_with_sharded_params():
    """Generation over TP+FSDP-sharded params produces identical tokens to
    the unsharded run (GSPMD propagates shardings through prefill + the
    decode scan — the sharded big-model inference path)."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.generation import beam_search
    from accelerate_tpu.parallel.sharding import make_sharding_plan
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    prompt = jnp.asarray([[5, 42, 7, 9]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    ref = generate(model, params, prompt, GenerationConfig(max_new_tokens=5))

    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=2, tp_size=4))
    plan = make_sharding_plan(params, acc.mesh, parallelism_config=acc.parallelism_config)
    sharded = jax.device_put(params, plan)
    out = generate(model, sharded, prompt, GenerationConfig(max_new_tokens=5))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    beam = beam_search(model, sharded, prompt, GenerationConfig(max_new_tokens=5), num_beams=3)
    assert beam.shape == (1, 5)
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def test_generate_from_quantized_params(tiny_model):
    """int8-quantized params decode natively: QuantizedTensor kernel leaves
    route through QuantizableDense -> the Pallas in-tile-dequant matmul (the
    bnb-analog inference path, reference utils/bnb.py:469), with no apply
    wrapper."""
    from accelerate_tpu.generation import beam_search
    from accelerate_tpu.utils.quantization import QuantizationConfig, quantize_params

    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7, 9]], jnp.int32)
    qparams = quantize_params(
        params, QuantizationConfig(load_in_8bit=True, min_size=1, skip_patterns=(
            "embed", "norm", "bias", "scale", "lm_head"))
    )
    from accelerate_tpu.utils.quantization import is_quantized

    assert any(is_quantized(x) for x in jax.tree_util.tree_leaves(
        qparams, is_leaf=is_quantized))
    out = generate(model, qparams, prompt, GenerationConfig(max_new_tokens=6))
    ref = generate(model, params, prompt, GenerationConfig(max_new_tokens=6))
    # int8 blockwise-absmax is tight enough that the tiny model's greedy
    # path is unchanged — a strong end-to-end dequant-correctness signal
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    beam = beam_search(model, qparams, prompt, GenerationConfig(max_new_tokens=4),
                       num_beams=3)
    assert beam.shape == (1, 4)


def test_generate_quantized_via_apply_wrapper(tiny_model):
    """The generic quantized_apply wrapper (for model families without
    QuantizableDense) still decodes correctly."""
    from accelerate_tpu.utils.quantization import (
        QuantizationConfig,
        quantize_params,
        quantized_apply,
    )

    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7, 9]], jnp.int32)
    qparams = quantize_params(params, QuantizationConfig(load_in_8bit=True, min_size=1))
    out = generate(model, qparams, prompt, GenerationConfig(max_new_tokens=6),
                   apply_fn=quantized_apply(model.apply))
    ref = generate(model, params, prompt, GenerationConfig(max_new_tokens=6))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.slow
def test_generate_streamed_matches_regular(tiny_model):
    """Layer-streamed decode (the over-HBM inference mode) matches the
    one-jit generate.  Token streams are compared where logits are
    decisive; near-ties (the per-layer jits fuse differently, so float
    noise can flip an argmax between two ~equal logits) are tolerated by
    also accepting positions where the manual no-cache forward agrees with
    the streamed choice."""
    from accelerate_tpu.generation import generate_streamed
    from accelerate_tpu.utils.quantization import QuantizationConfig, quantize_params

    model, params = tiny_model
    prompt = jnp.asarray([[5, 42, 7]], jnp.int32)
    cfg = GenerationConfig(max_new_tokens=4)
    ref = generate(model, params, prompt, cfg)
    st = generate_streamed(model, params, prompt, cfg)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(st))

    # variable-length rows + EOS padding + int8 leaves: compare step tokens,
    # accepting a divergence only if the two candidates' full-forward logits
    # are within float noise of each other at that step (a genuine tie)
    batch = jnp.asarray([[5, 42, 7, 9], [11, 3, 0, 0]], jnp.int32)
    lens = jnp.asarray([4, 2])
    cfg = GenerationConfig(max_new_tokens=5, eos_token_id=2)
    qparams = quantize_params(params, QuantizationConfig(load_in_8bit=True, min_size=1))
    for p in (params, qparams):
        ref = np.asarray(generate(model, p, batch, cfg, prompt_lengths=lens))
        st = np.asarray(generate_streamed(model, p, batch, cfg, prompt_lengths=lens))
        if np.array_equal(ref, st):
            continue
        # divergences must start at a near-tie, and the streams must agree
        # up to the first divergent step per row
        for r in range(ref.shape[0]):
            row_ref, row_st = ref[r], st[r]
            if np.array_equal(row_ref, row_st):
                continue
            first = int(np.argmax(row_ref != row_st))
            seq = np.concatenate([np.asarray(batch[r][: int(lens[r])]), row_st[:first]])
            logits = np.asarray(
                model.apply(p, jnp.asarray(seq[None], jnp.int32))
            )[0, -1].astype(np.float32)
            a, b = int(row_ref[first]), int(row_st[first])
            assert abs(logits[a] - logits[b]) < 2e-2, (
                f"row {r} step {first}: {a} vs {b} not a near-tie "
                f"({logits[a]:.4f} vs {logits[b]:.4f})"
            )


def test_generate_streamed_prefetch_logits_equal(tiny_model):
    """The layer double buffer (ops/streaming.LayerPrefetcher) only moves
    WHERE the H2D copy is dispatched — prefetch-on and prefetch-off must
    produce bit-identical logits at every forward, and identical tokens.
    The prefetcher's accounting must show the lookahead actually engaged."""
    from accelerate_tpu.generation import generate_streamed
    from accelerate_tpu.ops.streaming import StreamStats

    model, params = tiny_model
    batch = jnp.asarray([[5, 42, 7, 9], [11, 3, 2, 0]], jnp.int32)
    lens = jnp.asarray([4, 3])
    cfg = GenerationConfig(max_new_tokens=5, eos_token_id=2)

    logits_off: list = []
    off = generate_streamed(model, params, batch, cfg, prompt_lengths=lens,
                            prefetch=False, capture_logits=logits_off)
    stats = StreamStats()
    logits_on: list = []
    on = generate_streamed(model, params, batch, cfg, prompt_lengths=lens,
                           prefetch=True, stream_stats=stats,
                           capture_logits=logits_on)
    np.testing.assert_array_equal(np.asarray(off), np.asarray(on))
    assert len(logits_on) == len(logits_off)
    for a, b in zip(logits_on, logits_off):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # accounting: every layer of every forward fetched exactly once, all but
    # the cold first already in flight when requested (wrap prefetch)
    n_layers = model.config.num_hidden_layers
    assert stats.fetches >= len(logits_on) * n_layers
    assert stats.prefetch_hits >= len(logits_on) * n_layers - 1
    assert stats.h2d_bytes > 0 and stats.wall_s > 0


def test_generate_streamed_from_offload_store(tmp_path, tiny_model):
    """generate_streamed decodes straight from an OffloadStore's memmap
    leaves (the disk tier): the prefetcher uploads each layer from its .dat
    files, and tokens match the in-memory params."""
    from accelerate_tpu.big_modeling import offload_state_dict, offload_store_params
    from accelerate_tpu.generation import generate_streamed

    model, params = tiny_model
    flat, _ = jax.tree_util.tree_flatten_with_path(params)

    def key_of(path):
        parts = []
        for k in path:
            for attr in ("key", "idx", "name"):
                if hasattr(k, attr):
                    parts.append(str(getattr(k, attr)))
                    break
        return "/".join(parts)

    store = offload_state_dict(
        str(tmp_path), {key_of(path): np.asarray(leaf) for path, leaf in flat}
    )
    disk_params = offload_store_params(store)
    assert isinstance(
        jax.tree_util.tree_leaves(disk_params["params"]["layers_0"])[0], np.memmap
    )
    prompt = jnp.asarray([[5, 42, 7]], jnp.int32)
    cfg = GenerationConfig(max_new_tokens=4)
    ref = generate_streamed(model, params, prompt, cfg)
    disk = generate_streamed(model, disk_params, prompt, cfg)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(disk))


def test_generate_from_scan_layout_params():
    """A scan_layers-trained state generates directly: generate() converts
    to the unrolled layout transparently (unstack + config replace)."""
    import dataclasses

    from accelerate_tpu.models.llama import stack_layer_params

    cfg = LlamaConfig.tiny(scan_layers=True)
    model = LlamaForCausalLM(cfg)
    un_model = LlamaForCausalLM(dataclasses.replace(cfg, scan_layers=False))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 255, (1, 8)), jnp.int32)
    un_params = un_model.init(jax.random.PRNGKey(0), ids)
    out_scan = generate(model, stack_layer_params(un_params), ids,
                        GenerationConfig(max_new_tokens=4))
    out_ref = generate(un_model, un_params, ids, GenerationConfig(max_new_tokens=4))
    np.testing.assert_array_equal(np.asarray(out_scan), np.asarray(out_ref))
