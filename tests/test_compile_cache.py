"""Scoped compilation-cache management (utils/compile_cache.py): every
harness gets a cache directory keyed by toolchain + tag + scope, retiring
the documented shared-/tmp corruption flake (concurrent jax processes) and
stale-version reuse — plus the prewarm pack distribution + version-keyed
eviction (the closing slice of ROADMAP item 4)."""

import json
import tarfile
from pathlib import Path

import jax
import pytest

from accelerate_tpu.utils.compile_cache import (
    PREWARM_MANIFEST,
    enable_scoped_compilation_cache,
    export_prewarm,
    load_prewarm,
    scoped_cache_dir,
    sweep_stale_versions,
    toolchain_version_key,
)


def test_scoped_dir_keys_on_toolchain_and_tag(tmp_path):
    d_tests = scoped_cache_dir("tests", root=str(tmp_path))
    d_bench = scoped_cache_dir("bench", root=str(tmp_path))
    assert d_tests != d_bench
    assert f"jax{jax.__version__}" in d_tests
    from pathlib import Path

    assert Path(d_tests).is_dir() and Path(d_bench).is_dir()


def test_scope_env_isolates_concurrent_runs(tmp_path, monkeypatch):
    base = scoped_cache_dir("tests", root=str(tmp_path))
    monkeypatch.setenv("ACCELERATE_JAX_CACHE_SCOPE", "runA")
    a = scoped_cache_dir("tests", root=str(tmp_path))
    monkeypatch.setenv("ACCELERATE_JAX_CACHE_SCOPE", "runB")
    b = scoped_cache_dir("tests", root=str(tmp_path))
    assert len({base, a, b}) == 3
    # the pytest-xdist worker id scopes automatically (the exact concurrent-
    # suite shape that corrupted the flat /tmp dir)
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE")
    monkeypatch.setenv("PYTEST_XDIST_WORKER", "gw3")
    assert scoped_cache_dir("tests", root=str(tmp_path)).endswith("tests-gw3")


def test_enable_points_jax_at_scoped_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE", raising=False)
    monkeypatch.delenv("PYTEST_XDIST_WORKER", raising=False)
    prev = jax.config.jax_compilation_cache_dir
    try:
        d = enable_scoped_compilation_cache("cache-test", root=str(tmp_path))
        if d is None:  # pragma: no cover - older jax without the knobs
            pytest.skip("jax build lacks compilation-cache config knobs")
        assert jax.config.jax_compilation_cache_dir == d
        assert d.startswith(str(tmp_path))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


# ---------------------------------------------------------------------------
# prewarm pack + version-keyed eviction
# ---------------------------------------------------------------------------


def _fake_warm_cache(root, tag, entries):
    d = Path(scoped_cache_dir(tag, root=str(root)))
    for name, payload in entries.items():
        (d / name).write_bytes(payload)
    return d


def test_prewarm_export_load_roundtrip(tmp_path, monkeypatch):
    """A warmed cache packs into one toolchain-keyed archive; loading it on
    a fresh host (root) reproduces every entry byte-for-byte."""
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE", raising=False)
    monkeypatch.delenv("PYTEST_XDIST_WORKER", raising=False)
    entries = {"prog_a.bin": b"\x01\x02xla", "prog_b.bin": b"\x03serving"}
    _fake_warm_cache(tmp_path / "src", "deploy", entries)
    pack = export_prewarm(str(tmp_path / "prewarm.tar"), "deploy",
                          root=str(tmp_path / "src"))
    with tarfile.open(pack) as tar:
        manifest = json.loads(tar.extractfile(PREWARM_MANIFEST).read())
    assert manifest["version_key"] == toolchain_version_key()
    assert manifest["entries"] == sorted(entries)

    report = load_prewarm(pack, "deploy", root=str(tmp_path / "dst"))
    assert report["loaded"] == 2 and not report["stale"]
    dst = Path(scoped_cache_dir("deploy", root=str(tmp_path / "dst")))
    for name, payload in entries.items():
        assert (dst / name).read_bytes() == payload


def test_prewarm_refuses_foreign_toolchain(tmp_path, monkeypatch):
    """A pack built by a different jax/Python build is refused (its entries
    could never hit) — loaded=0, stale=True, nothing extracted; a broken
    archive degrades the same way instead of failing the deploy."""
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE", raising=False)
    monkeypatch.delenv("PYTEST_XDIST_WORKER", raising=False)
    _fake_warm_cache(tmp_path / "src", "deploy", {"prog.bin": b"x"})
    pack = export_prewarm(str(tmp_path / "p.tar"), "deploy",
                          root=str(tmp_path / "src"))
    # rewrite the manifest to a foreign toolchain
    foreign = str(tmp_path / "foreign.tar")
    with tarfile.open(pack) as tar, tarfile.open(foreign, "w") as out:
        for m in tar.getmembers():
            data = tar.extractfile(m).read()
            if m.name == PREWARM_MANIFEST:
                data = json.dumps({"version_key": "jax0.0.1-py2.7",
                                   "tag": "deploy", "entries": ["prog.bin"]}).encode()
            m.size = len(data)
            import io

            out.addfile(m, io.BytesIO(data))
    report = load_prewarm(foreign, "deploy", root=str(tmp_path / "dst"))
    assert report["stale"] and report["loaded"] == 0
    dst = Path(scoped_cache_dir("deploy", root=str(tmp_path / "dst")))
    assert not (dst / "prog.bin").exists()
    # truncated/garbage archive: same degrade, never a raise
    bad = tmp_path / "bad.tar"
    bad.write_bytes(b"not a tar")
    rep2 = load_prewarm(str(bad), "deploy", root=str(tmp_path / "dst"))
    assert rep2["stale"] and rep2["loaded"] == 0
    # a valid tar with NO manifest member (foreign pack): refused, no raise
    noman = tmp_path / "nomanifest.tar"
    with tarfile.open(noman, "w") as out:
        import io

        info = tarfile.TarInfo("cache/prog.bin")
        info.size = 1
        out.addfile(info, io.BytesIO(b"x"))
    rep3 = load_prewarm(str(noman), "deploy", root=str(tmp_path / "dst"))
    assert rep3["stale"] and rep3["loaded"] == 0


def test_load_prewarm_sweeps_stale_version_dirs(tmp_path, monkeypatch):
    """Version-keyed eviction: loading (or sweeping directly) removes every
    cache-root subdir keyed by a different toolchain, and ONLY those."""
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE", raising=False)
    monkeypatch.delenv("PYTEST_XDIST_WORKER", raising=False)
    root = tmp_path / "cache"
    _fake_warm_cache(root, "deploy", {"prog.bin": b"x"})
    stale = root / "jax0.3.0-py3.8" / "deploy"
    stale.mkdir(parents=True)
    (stale / "dead.bin").write_bytes(b"stale")
    pack = export_prewarm(str(tmp_path / "p.tar"), "deploy", root=str(root))
    report = load_prewarm(pack, "deploy", root=str(root))
    assert report["swept"] == ["jax0.3.0-py3.8"]
    assert not stale.exists()
    assert (root / toolchain_version_key()).is_dir()  # current survives
    assert sweep_stale_versions(str(root)) == []      # idempotent


def test_scoped_cache_dir_per_launched_process(tmp_path, monkeypatch):
    """Concurrent launched processes never share a cache dir: the scope is
    keyed by the launcher's ACCELERATE_PROCESS_ID (reading
    jax.process_index() would initialize the backend before the worker's
    jax.distributed.initialize)."""
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE", raising=False)
    monkeypatch.delenv("PYTEST_XDIST_WORKER", raising=False)
    root = str(tmp_path)
    monkeypatch.setenv("ACCELERATE_PROCESS_ID", "0")
    d0 = scoped_cache_dir("tests", root=root)
    monkeypatch.setenv("ACCELERATE_PROCESS_ID", "1")
    d1 = scoped_cache_dir("tests", root=root)
    assert d0 != d1
    assert d0.endswith("tests-proc0") and d1.endswith("tests-proc1")
    # unlaunched processes keep the bare tag (cache reuse across runs)
    monkeypatch.delenv("ACCELERATE_PROCESS_ID", raising=False)
    assert scoped_cache_dir("tests", root=root).endswith("/tests")
    # the xdist/explicit scope composes with the process scope
    monkeypatch.setenv("ACCELERATE_JAX_CACHE_SCOPE", "w3")
    monkeypatch.setenv("ACCELERATE_PROCESS_ID", "2")
    assert scoped_cache_dir("tests", root=root).endswith("tests-w3-proc2")
