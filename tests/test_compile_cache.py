"""Scoped compilation-cache management (utils/compile_cache.py): every
harness gets a cache directory keyed by toolchain + tag + scope, retiring
the documented shared-/tmp corruption flake (concurrent jax processes) and
stale-version reuse."""

import jax
import pytest

from accelerate_tpu.utils.compile_cache import (
    enable_scoped_compilation_cache,
    scoped_cache_dir,
)


def test_scoped_dir_keys_on_toolchain_and_tag(tmp_path):
    d_tests = scoped_cache_dir("tests", root=str(tmp_path))
    d_bench = scoped_cache_dir("bench", root=str(tmp_path))
    assert d_tests != d_bench
    assert f"jax{jax.__version__}" in d_tests
    from pathlib import Path

    assert Path(d_tests).is_dir() and Path(d_bench).is_dir()


def test_scope_env_isolates_concurrent_runs(tmp_path, monkeypatch):
    base = scoped_cache_dir("tests", root=str(tmp_path))
    monkeypatch.setenv("ACCELERATE_JAX_CACHE_SCOPE", "runA")
    a = scoped_cache_dir("tests", root=str(tmp_path))
    monkeypatch.setenv("ACCELERATE_JAX_CACHE_SCOPE", "runB")
    b = scoped_cache_dir("tests", root=str(tmp_path))
    assert len({base, a, b}) == 3
    # the pytest-xdist worker id scopes automatically (the exact concurrent-
    # suite shape that corrupted the flat /tmp dir)
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE")
    monkeypatch.setenv("PYTEST_XDIST_WORKER", "gw3")
    assert scoped_cache_dir("tests", root=str(tmp_path)).endswith("tests-gw3")


def test_enable_points_jax_at_scoped_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("ACCELERATE_JAX_CACHE_SCOPE", raising=False)
    monkeypatch.delenv("PYTEST_XDIST_WORKER", raising=False)
    prev = jax.config.jax_compilation_cache_dir
    try:
        d = enable_scoped_compilation_cache("cache-test", root=str(tmp_path))
        if d is None:  # pragma: no cover - older jax without the knobs
            pytest.skip("jax build lacks compilation-cache config knobs")
        assert jax.config.jax_compilation_cache_dir == d
        assert d.startswith(str(tmp_path))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
