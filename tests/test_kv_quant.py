"""Quantized KV pages (ISSUE 17 tentpole leg 2): int8/fp8 page codes with
per-(kv-head, page) running-amax scales.  Pins the acceptance contracts:

- greedy ``generate_paged`` under int8 KV stays within the pinned logit
  tolerance of the dense-cache reference and is BITWISE run-to-run
  deterministic;
- the capacity ladder delivers >= 1.9x tokens per HBM byte once
  ``page_size * head_dim`` amortizes the scales;
- quantize-on-write semantics: roundtrip error bounded by the page amax,
  running-amax rescale keeps one scale per page, an offset-0 write resets
  a recycled page's range;
- the knob surface (``ServingPlugin.kv_dtype`` + env default), the
  kv_dtype-seeded prefix-cache hashes, and the transfer handshake's
  dtype-mismatch rejection.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate, generate_paged
from accelerate_tpu.models.llama import (
    KV_QUANT_QMAX,
    LlamaConfig,
    LlamaForCausalLM,
    dequantize_kv_pages,
    init_paged_cache,
    paged_gather_kv,
    paged_write_kv_quantized,
    resolve_kv_dtype,
)
from accelerate_tpu.serving import Request, ServingEngine, kv_pool_accounting
from accelerate_tpu.serving.paged_cache import kv_page_bytes
from accelerate_tpu.serving.prefix_cache import PrefixCache, block_hashes
from accelerate_tpu.serving.transfer import PagedKVTransport, page_bytes
from accelerate_tpu.utils.dataclasses import ServingPlugin


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _plugin(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("decode_kernel", "native")
    return ServingPlugin(**kw)


# ---------------------------------------------------------------------------
# knob surface
# ---------------------------------------------------------------------------


def test_resolve_kv_dtype_normalization():
    for dense in (None, "", "bf16"):
        assert resolve_kv_dtype(dense) is None
    assert resolve_kv_dtype("int8") == "int8"
    assert resolve_kv_dtype("fp8") == "fp8"
    with pytest.raises(ValueError, match="kv_dtype"):
        resolve_kv_dtype("int4")


def test_serving_plugin_kv_dtype_knob(monkeypatch):
    assert _plugin().kv_dtype == "bf16"
    assert _plugin(kv_dtype="INT8").kv_dtype == "int8"  # case-folded
    monkeypatch.setenv("ACCELERATE_SERVE_KV_DTYPE", "fp8")
    assert _plugin().kv_dtype == "fp8"                  # env default
    assert _plugin(kv_dtype="bf16").kv_dtype == "bf16"  # explicit wins
    with pytest.raises(ValueError, match="kv_dtype"):
        _plugin(kv_dtype="int4")


def test_quantized_pool_layout():
    cfg = LlamaConfig.tiny()
    dense = init_paged_cache(cfg, 8, 4, 2, 4)
    quant = init_paged_cache(cfg, 8, 4, 2, 4, kv_dtype="int8")
    assert "k_scales" not in dense["layers"][0]
    layer = quant["layers"][0]
    assert layer["k_pages"].dtype == jnp.int8
    assert layer["k_scales"].shape == (cfg.num_key_value_heads, 8)
    assert layer["v_scales"].dtype == jnp.float32
    fp8 = init_paged_cache(cfg, 8, 4, 2, 4, kv_dtype="fp8")
    assert fp8["layers"][0]["v_pages"].dtype == jnp.float8_e4m3fn


# ---------------------------------------------------------------------------
# quantize-on-write semantics
# ---------------------------------------------------------------------------


def _empty_page_pool(hkv=2, num_pages=4, page=4, d=16, kv_dtype="int8"):
    pages = jnp.zeros((hkv, num_pages, page, d),
                      jnp.int8 if kv_dtype == "int8" else jnp.float8_e4m3fn)
    scales = jnp.zeros((hkv, num_pages), jnp.float32)
    return pages, scales


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
def test_paged_write_quantized_roundtrip(kv_dtype):
    """Write a full page, dequantize, and bound the error by the
    quantization step (amax / QMAX); the same call is bitwise
    reproducible (duplicate scatters all see the final amax)."""
    pages, scales = _empty_page_pool(kv_dtype=kv_dtype)
    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.normal(size=(1, 4, 2, 16)), jnp.float32)
    ids = jnp.zeros((1, 4), jnp.int32)
    offs = jnp.arange(4, dtype=jnp.int32)[None]
    p1, s1 = paged_write_kv_quantized(pages, scales, vals, ids, offs, kv_dtype)
    p2, s2 = paged_write_kv_quantized(pages, scales, vals, ids, offs, kv_dtype)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))

    deq = dequantize_kv_pages(p1, s1, kv_dtype, jnp.float32)  # [Hkv,P,page,D]
    got = np.asarray(deq[:, 0]).transpose(1, 0, 2)            # [page,Hkv,D]
    want = np.asarray(vals[0])
    amax = np.abs(want).max(axis=(0, 2), keepdims=True)       # per kv-head
    # int8's step is uniform (amax/127); fp8 e4m3's is relative to the
    # element (3 mantissa bits -> <= 2^-3 round-to-nearest).  Allow 2
    # steps: the running-amax rescale pass can add one more rounding.
    step = amax / KV_QUANT_QMAX[kv_dtype]
    tol = 2.0 * np.maximum(step, np.abs(want) * 2.0 ** -3)
    assert np.max(np.abs(got - want) / tol) < 1.0


def test_paged_write_running_amax_and_offset0_reset():
    pages, scales = _empty_page_pool()
    small = jnp.full((1, 2, 2, 16), 0.1, jnp.float32)
    big = jnp.full((1, 1, 2, 16), 10.0, jnp.float32)
    pid = jnp.zeros((1, 2), jnp.int32)

    # open page 0 with small rows: scale is the small amax
    pages, scales = paged_write_kv_quantized(
        pages, scales, small, pid, jnp.asarray([[0, 1]], jnp.int32), "int8")
    assert np.allclose(np.asarray(scales[:, 0]), 0.1, rtol=1e-5)

    # a later big row grows the running amax; earlier rows rescale in place
    pages, scales = paged_write_kv_quantized(
        pages, scales, big, pid[:, :1], jnp.asarray([[2]], jnp.int32), "int8")
    assert np.allclose(np.asarray(scales[:, 0]), 10.0, rtol=1e-5)
    deq = np.asarray(dequantize_kv_pages(pages, scales, "int8", jnp.float32))
    step = 10.0 / 127.0
    assert np.max(np.abs(deq[:, 0, :2] - 0.1)) <= 2 * step
    assert np.max(np.abs(deq[:, 0, 2] - 10.0)) <= step

    # recycling the page: an offset-0 write resets the amax — the new
    # tenant never inherits the old 10.0 range
    pages, scales = paged_write_kv_quantized(
        pages, scales, small[:, :1], pid[:, :1],
        jnp.asarray([[0]], jnp.int32), "int8")
    assert np.allclose(np.asarray(scales[:, 0]), 0.1, rtol=1e-5)
    deq = np.asarray(dequantize_kv_pages(pages, scales, "int8", jnp.float32))
    assert np.max(np.abs(deq[:, 0, 0] - 0.1)) <= 2 * 0.1 / 127.0


# ---------------------------------------------------------------------------
# capacity ladder + accounting
# ---------------------------------------------------------------------------


def test_capacity_ladder_at_least_1p9x():
    """The acceptance floor: >= 1.9x token capacity per HBM byte once
    page_size * head_dim amortizes the per-page scales (tiny geometry:
    page 16 x D 16 -> 4096 dense bytes vs 2080 quantized = 1.969x)."""
    cfg = LlamaConfig.tiny()
    for kv_dtype in ("int8", "fp8"):
        acct = kv_pool_accounting(cfg, 64, 16, 2, kv_dtype)
        assert acct["kv_dtype"] == kv_dtype
        assert acct["capacity_vs_bf16"] >= 1.9
        want = (2 * cfg.num_hidden_layers * 16 * cfg.num_key_value_heads
                * cfg.head_dim
                + 2 * cfg.num_hidden_layers * cfg.num_key_value_heads * 4)
        assert acct["bytes_per_page"] == want == kv_page_bytes(cfg, 16, 2, kv_dtype)
    # dense accounting is unchanged and carries no ladder keys
    dense = kv_pool_accounting(cfg, 64, 16, 2)
    assert "capacity_vs_bf16" not in dense
    # the transfer wire unit routes through the SAME formula (twin exactness
    # by construction)
    assert page_bytes(cfg, 16, 2, kv_dtype="int8") == kv_page_bytes(cfg, 16, 2, "int8")


# ---------------------------------------------------------------------------
# model-level parity (the pinned tolerance) + end-to-end determinism
# ---------------------------------------------------------------------------


def _paged_prefill_logits(model, params, ids, kv_dtype):
    page_size, slots, pps = 4, 1, 4
    pc = init_paged_cache(model.config, 8, page_size, slots, pps,
                          kv_dtype=kv_dtype or None)
    bt = jnp.arange(slots * pps, dtype=jnp.int32).reshape(slots, pps)
    keep = ("k_pages", "v_pages", "k_scales", "v_scales")
    layers = [{**{k: l[k] for k in keep if k in l}, "block_tables": bt}
              for l in pc["layers"]]
    n = ids.shape[1]
    lg, _ = model.apply(
        params, ids, positions=jnp.arange(n)[None],
        cache=layers, cache_write_mask=jnp.ones((1, n), bool),
    )
    return lg


@pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
def test_paged_quantized_logits_within_pinned_tolerance(tiny_model, kv_dtype):
    """Prefill through quantized pages reproduces the dense-cache logits
    within the pinned envelope — the fp32-reference tolerance the ISSUE
    acceptance names (measured ~0.4% relative on the tiny model; pinned
    at 5% of the logit range so real regressions, not quantization noise,
    trip it)."""
    model, params = tiny_model
    ids = jnp.asarray([[3, 17, 99, 4, 250, 7, 12, 63]], jnp.int32)
    ref = np.asarray(model.apply(params, ids), np.float32)
    got = np.asarray(_paged_prefill_logits(model, params, ids, kv_dtype), np.float32)
    scale = np.abs(ref).max()
    assert np.max(np.abs(got - ref)) < 0.05 * scale
    # and the quantized path really quantized (not silently dense)
    assert np.max(np.abs(got - ref)) > 0


def test_generate_paged_int8_deterministic_and_close_to_reference(tiny_model):
    """End-to-end acceptance: greedy paged decode over int8 KV pages is
    BITWISE run-to-run deterministic, and tracks the dense reference —
    the first emitted token of every row matches exactly (one decode step
    of quantization noise never flips the tiny model's argmax) and overall
    token agreement stays above the floor.  Exact full-sequence match is
    NOT the contract: a random-init model's near-uniform logits let one
    argmax flip cascade, which says nothing about the KV representation.
    """
    model, params = tiny_model
    prompts = [[3, 17, 99, 4, 250], [7, 12, 63], [5, 5, 9, 20, 77, 120, 8]]
    maxlen = max(len(p) for p in prompts)
    ids = jnp.asarray([p + [0] * (maxlen - len(p)) for p in prompts], jnp.int32)
    plens = jnp.asarray([len(p) for p in prompts], jnp.int32)
    gcfg = GenerationConfig(max_new_tokens=12)
    ref = np.asarray(generate(model, params, ids, gcfg, prompt_lengths=plens))
    plug = _plugin(kv_dtype="int8")
    out1 = np.asarray(generate_paged(model, params, ids, gcfg,
                                     prompt_lengths=plens, serving_plugin=plug))
    out2 = np.asarray(generate_paged(model, params, ids, gcfg,
                                     prompt_lengths=plens, serving_plugin=plug))
    np.testing.assert_array_equal(out1, out2)   # bitwise run-to-run
    np.testing.assert_array_equal(out1[:, 0], ref[:, 0])
    assert (out1 == ref).mean() >= 0.5


# ---------------------------------------------------------------------------
# prefix-cache hash seeding + transfer handshake
# ---------------------------------------------------------------------------


def test_block_hashes_seeded_by_kv_dtype():
    """A quantized pool's page CONTENT is codes+scale, so its prefix hashes
    must never collide with a dense pool's (or another quant dtype's) for
    the same prompt — the kv_dtype seeds the chain root."""
    prompt = (3, 17, 99, 4, 250, 7, 12, 63)
    dense = block_hashes(prompt, 4)
    assert block_hashes(prompt, 4, kv_dtype="bf16") == dense  # bf16 == dense
    int8 = block_hashes(prompt, 4, kv_dtype="int8")
    fp8 = block_hashes(prompt, 4, kv_dtype="fp8")
    assert len({dense[0], int8[0], fp8[0]}) == 3
    # PrefixCache carries the seed so engine-internal hashing matches
    assert PrefixCache(4, kv_dtype="int8").block_hashes(prompt) == int8
    assert PrefixCache(4).block_hashes(prompt) == dense


def test_transport_rejects_kv_dtype_mismatch(tiny_model):
    model, params = tiny_model
    gcfg = GenerationConfig(max_new_tokens=4)
    src = ServingEngine(model, params, _plugin(kv_dtype="int8"), gcfg)
    dst = ServingEngine(model, params, _plugin(), gcfg)
    with pytest.raises(ValueError, match="KV page dtypes must match"):
        PagedKVTransport(src, dst)
    # matched quantized pair: constructs, and the wire unit is the
    # codes+scales page size (half the dense bytes and change)
    dst8 = ServingEngine(model, params, _plugin(kv_dtype="int8"), gcfg)
    t = PagedKVTransport(src, dst8)
    cfg = model.config
    assert t._page_bytes == kv_page_bytes(cfg, 4, 2, "int8") \
        < kv_page_bytes(cfg, 4, 2)
