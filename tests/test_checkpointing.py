"""Checkpoint/resume tests (mirror of reference tests/test_state_checkpointing.py:
save/load roundtrip, automatic naming + retention GC, RNG restore, custom
objects, model export/merge)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.checkpointing import (
    list_checkpoints,
    load_model_params,
    merge_weights,
    parse_size,
    save_model,
)
from accelerate_tpu.test_utils.training import (
    make_regression_loader,
    regression_init_params,
    regression_loss_fn,
)
from accelerate_tpu.utils.dataclasses import ProjectConfiguration


def _setup(tmp_path, **acc_kwargs):
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True, total_limit=2
        ),
        **acc_kwargs,
    )
    dl = acc.prepare(make_regression_loader(batch_size=16))
    state = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    step = acc.prepare_train_step(regression_loss_fn)
    return acc, dl, state, step


def test_save_load_roundtrip(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    for batch in dl:
        state, _ = step(state, batch)
    ckpt_dir = acc.save_state(train_state=state)
    a_saved = float(state.params["a"])
    step_saved = int(state.step)

    # continue training, then restore
    for batch in dl:
        state, _ = step(state, batch)
    assert float(state.params["a"]) != a_saved

    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    restored = acc.load_state(ckpt_dir, train_state=template)
    assert float(restored.params["a"]) == a_saved
    assert int(restored.step) == step_saved
    # optimizer state restored too
    assert float(restored.opt_state[0].mu["a"]) != 0.0


def test_roundtrip_with_non_jax_array_leaf(tmp_path):
    """restore_args must cover every template key: a numpy leaf inside the
    state (e.g. host-side stats in opt_state) previously made orbax raise a
    tree-structure mismatch instead of restoring."""
    acc, dl, state, step = _setup(tmp_path)
    state = state.replace(opt_state=(state.opt_state, np.arange(3, dtype=np.float32)))

    def _unwrap_step(st, batch):
        inner = st.replace(opt_state=st.opt_state[0])
        new_inner, m = step(inner, batch)
        return new_inner.replace(opt_state=(new_inner.opt_state, st.opt_state[1])), m

    for batch in dl:
        state, _ = _unwrap_step(state, batch)
    ckpt_dir = acc.save_state(train_state=state)
    a_saved = float(state.params["a"])

    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    template = template.replace(opt_state=(template.opt_state, np.zeros(3, dtype=np.float32)))
    restored = acc.load_state(ckpt_dir, train_state=template)
    assert float(restored.params["a"]) == a_saved
    np.testing.assert_allclose(np.asarray(restored.opt_state[1]), np.arange(3, dtype=np.float32))


def test_automatic_naming_and_retention(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    for i in range(3):
        acc.save_state(train_state=state)
    ckpts = list_checkpoints(str(tmp_path))
    # total_limit=2: oldest GC'd
    assert [os.path.basename(c) for c in ckpts] == ["checkpoint_1", "checkpoint_2"]


def test_async_save_immediate_save_and_retention_race(tmp_path):
    """save -> immediate save -> third save triggering retention GC: every
    async write must be awaited before the next writer (and before rmtree),
    so all surviving checkpoints load intact (VERDICT r4 weak #1)."""
    acc, dl, state, step = _setup(tmp_path)
    states = []
    dirs = []
    for batch in dl:  # 3 saves back-to-back, one step apart
        state, _ = step(state, batch)
        states.append(float(state.params["a"]))
        dirs.append(acc.save_state(train_state=state, async_save=True))
        if len(dirs) == 3:
            break
    # the third write is still in flight: its directory publishes only at
    # commit (atomic tmp+rename), so drain before listing.  total_limit=2:
    # first dir GC'd — and only after its write finished.
    acc.wait_for_checkpoint()
    ckpts = list_checkpoints(str(tmp_path))
    assert [os.path.basename(c) for c in ckpts] == ["checkpoint_1", "checkpoint_2"]
    for i, ckpt in enumerate(ckpts, start=1):
        template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
        restored = acc.load_state(ckpt, train_state=template)
        assert float(restored.params["a"]) == states[i]


def test_async_save_then_resume(tmp_path):
    """load_state immediately after an async save must see the full write."""
    acc, dl, state, step = _setup(tmp_path)
    for batch in dl:
        state, _ = step(state, batch)
    ckpt_dir = acc.save_state(train_state=state, async_save=True)
    assert acc._pending_checkpointer is not None
    a_saved = float(state.params["a"])
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    restored = acc.load_state(ckpt_dir, train_state=template)  # waits internally
    assert acc._pending_checkpointer is None
    assert float(restored.params["a"]) == a_saved
    assert int(restored.step) == int(state.step)


def test_end_training_flushes_async_save(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    batch = next(iter(dl))
    state, _ = step(state, batch)
    ckpt_dir = acc.save_state(train_state=state, async_save=True)
    assert acc._pending_checkpointer is not None
    first_ckptr = acc._async_checkpointer
    # the AsyncCheckpointer is long-lived: a second save reuses it
    acc.save_state(train_state=state, async_save=True)
    assert acc._async_checkpointer is first_ckptr
    acc.end_training()
    assert acc._pending_checkpointer is None
    # terminal: the cached checkpointer's threads are released
    assert acc._async_checkpointer is None
    # the flushed checkpoint is complete on disk
    template = acc.create_train_state(regression_init_params(), optax.adam(0.05))
    restored = acc.load_state(ckpt_dir, train_state=template)
    assert float(restored.params["a"]) == float(state.params["a"])


def test_save_publishes_atomically_with_manifest(tmp_path):
    """Every save stages under checkpoint_<i>.tmp and publishes with one
    os.replace: after it returns there is a manifest, no staging dir, and
    the directory verifies (docs/resilience.md)."""
    from accelerate_tpu.checkpointing import verify_checkpoint

    acc, dl, state, step = _setup(tmp_path)
    ckpt = acc.save_state(train_state=state)
    assert os.path.exists(os.path.join(ckpt, "checkpoint_manifest.json"))
    assert not list((tmp_path / "checkpoints").glob("*.tmp"))
    ok, problems = verify_checkpoint(ckpt)
    assert ok, problems

    # async saves publish at commit through the same atomic path
    ckpt2 = acc.save_state(train_state=state, async_save=True)
    acc.wait_for_checkpoint()
    assert not list((tmp_path / "checkpoints").glob("*.tmp"))
    ok, problems = verify_checkpoint(ckpt2)
    assert ok, problems


def test_stale_tmp_dir_is_swept_on_next_save(tmp_path):
    """A torn write from a crashed run (checkpoint_*.tmp) is never
    load-visible and the next save sweeps it."""
    from accelerate_tpu.checkpointing import list_checkpoints as _lc

    acc, dl, state, step = _setup(tmp_path)
    acc.save_state(train_state=state)
    stale = tmp_path / "checkpoints" / "checkpoint_9.tmp"
    stale.mkdir(parents=True)
    (stale / "garbage.bin").write_bytes(b"\x00" * 16)
    assert all(".tmp" not in os.path.basename(c) for c in _lc(str(tmp_path)))
    acc.save_state(train_state=state)
    assert not stale.exists()


def test_resumed_process_numbering_continues_past_existing(tmp_path):
    """A fresh ProjectConfiguration (iteration=0) over an existing checkpoint
    tree must keep numbering monotonic — otherwise post-resume saves would
    shadow the 'newest = highest index' ordering resume scans rely on."""
    acc, dl, state, step = _setup(tmp_path)
    acc.save_state(train_state=state)
    acc.save_state(train_state=state)

    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2, dl2, state2, step2 = _setup(tmp_path)  # iteration starts at 0 again
    ckpt = acc2.save_state(train_state=state2)
    assert os.path.basename(ckpt) == "checkpoint_2"


def test_rng_state_roundtrip(tmp_path):
    import random

    from accelerate_tpu.utils.random import set_seed

    acc, dl, state, step = _setup(tmp_path)
    set_seed(123)
    ckpt = acc.save_state(train_state=state)
    vals_expected = [random.random(), np.random.rand()]
    set_seed(999)
    acc.load_state(ckpt)
    vals_restored = [random.random(), np.random.rand()]
    assert vals_expected[0] == vals_restored[0]
    assert vals_expected[1] == vals_restored[1]


def test_custom_object_checkpointing(tmp_path):
    class Counter:
        def __init__(self):
            self.n = 0

        def state_dict(self):
            return {"n": self.n}

        def load_state_dict(self, sd):
            self.n = sd["n"]

    acc, dl, state, step = _setup(tmp_path)
    counter = Counter()
    acc.register_for_checkpointing(counter)
    counter.n = 7
    ckpt = acc.save_state(train_state=state)
    counter.n = 0
    acc.load_state(ckpt)
    assert counter.n == 7


def test_register_invalid_object_raises(tmp_path):
    acc, *_ = _setup(tmp_path)
    with pytest.raises(ValueError):
        acc.register_for_checkpointing(object())


def test_dataloader_state_saved(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    it = iter(dl)
    next(it)
    next(it)
    ckpt = acc.save_state(train_state=state)
    sd = json.loads(open(os.path.join(ckpt, "sampler_states.json")).read())
    assert sd[0]["batches_yielded"] == 2


def test_save_model_and_reload(tmp_path):
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    params = {"dense": {"kernel": jnp.arange(32.0).reshape(8, 4), "bias": jnp.ones(4)}}
    state = acc.create_train_state(params, optax.sgd(0.1))
    files = save_model(acc, state, str(tmp_path / "model"))
    assert files and files[0].endswith(".safetensors")
    loaded = load_model_params(str(tmp_path / "model"))
    np.testing.assert_allclose(loaded["dense"]["kernel"], np.arange(32.0).reshape(8, 4))


def test_save_model_sharded_index(tmp_path):
    acc = Accelerator()
    params = {f"w{i}": jnp.ones((64, 64)) for i in range(4)}  # 16KB each fp32
    state = acc.create_train_state(params, optax.sgd(0.1))
    files = save_model(acc, state, str(tmp_path / "model"), max_shard_size="20KB")
    assert len(files) > 1
    assert (tmp_path / "model" / "model.safetensors.index.json").exists()
    loaded = load_model_params(str(tmp_path / "model"))
    assert set(loaded.keys()) == {f"w{i}" for i in range(4)}


def test_merge_weights(tmp_path):
    acc, dl, state, step = _setup(tmp_path)
    ckpt = acc.save_state(train_state=state)
    out = merge_weights(ckpt, str(tmp_path / "merged"))
    assert os.path.exists(out)


def test_parse_size():
    assert parse_size("10GB") == 10 * 2**30
    assert parse_size("512 MB") == 512 * 2**20
    with pytest.raises(ValueError):
        parse_size("ten gigs")


def test_resume_mid_epoch(tmp_path):
    """save mid-epoch -> load in a fresh accelerator -> skip_first_batches
    continues from the right batch (reference skip_first_batches :4238)."""
    acc, dl, state, step = _setup(tmp_path)
    it = iter(dl)
    first = next(it)
    second = next(it)
    ckpt = acc.save_state(train_state=state)

    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc2, dl2, state2, step2 = _setup(tmp_path)
    acc2.load_state(ckpt)
    remaining = list(dl2)
    assert len(remaining) == 2  # 4 batches total, 2 consumed pre-save
    # the resumed loader starts at batch index 2 -> samples 32..47
    expected = [make_regression_loader(batch_size=16).dataset[i]["x"].item() for i in range(32, 48)]
    np.testing.assert_allclose(np.asarray(remaining[0]["x"]).ravel(), expected, rtol=1e-6)


def test_save_model_without_accelerator(tmp_path):
    """accelerator=None writes unconditionally (offline tooling path, e.g.
    authoring a checkpoint for the big-model inference benchmark)."""
    params = {"w": jnp.arange(16.0).reshape(4, 4)}
    files = save_model(None, params, str(tmp_path / "model"))
    assert files
    loaded = load_model_params(str(tmp_path / "model"))
    np.testing.assert_allclose(loaded["w"], np.arange(16.0).reshape(4, 4))


def test_wait_for_published_checkpoint(tmp_path):
    """The non-main-rank half of the rank-0 publish: the wait returns once
    the manifest (written LAST) is visible, and times out loudly — never
    silently — when the publish never lands."""
    import threading
    import time

    from accelerate_tpu.checkpointing import wait_for_published_checkpoint
    from accelerate_tpu.utils.constants import CHECKPOINT_MANIFEST_NAME

    ckpt = tmp_path / "checkpoint_0"
    with pytest.raises(TimeoutError, match="not visible"):
        wait_for_published_checkpoint(ckpt, timeout_s=0.2, poll_s=0.02)

    def publish():
        time.sleep(0.15)
        ckpt.mkdir()
        (ckpt / CHECKPOINT_MANIFEST_NAME).write_text("{}")

    t = threading.Thread(target=publish)
    t.start()
    wait_for_published_checkpoint(ckpt, timeout_s=5.0, poll_s=0.02)  # returns
    t.join()
    # verify=False (manifests disabled) waits on the directory alone
    bare = tmp_path / "checkpoint_1"
    bare.mkdir()
    wait_for_published_checkpoint(bare, verify=False, timeout_s=0.2)
