"""Collectives conformance tests (mirror of reference
test_utils/scripts/test_ops.py + tests/test_utils.py operations coverage)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.ops import operations as ops
from accelerate_tpu.parallel import collectives


def test_recursively_apply_nested():
    data = {"a": np.ones(2), "b": [np.zeros(3), (np.ones(1), "str")]}
    out = ops.recursively_apply(lambda t: t + 1, data)
    assert out["a"].tolist() == [2.0, 2.0]
    assert out["b"][0].tolist() == [1.0, 1.0, 1.0]
    assert out["b"][1][1] == "str"


def test_recursively_apply_namedtuple():
    from collections import namedtuple

    Point = namedtuple("Point", ["x", "y"])
    p = Point(np.ones(2), np.zeros(2))
    out = ops.recursively_apply(lambda t: t * 2, p)
    assert isinstance(out, Point)
    assert out.x.tolist() == [2.0, 2.0]


def test_recursively_apply_error_on_other():
    with pytest.raises(TypeError):
        ops.recursively_apply(lambda t: t, {"a": "str"}, error_on_other_type=True)


def test_send_to_device():
    batch = {"x": np.ones((2, 2)), "y": [np.zeros(3)]}
    out = ops.send_to_device(batch, jax.devices()[0])
    assert isinstance(out["x"], jax.Array)
    assert out["x"].devices() == {jax.devices()[0]}


def test_send_to_device_skip_keys():
    batch = {"x": np.ones(2), "meta": np.zeros(2)}
    out = ops.send_to_device(batch, jax.devices()[0], skip_keys=["meta"])
    assert isinstance(out["x"], jax.Array)
    assert isinstance(out["meta"], np.ndarray)


def test_get_data_structure_and_initialize():
    data = {"x": np.ones((2, 3), dtype=np.float32)}
    skel = ops.get_data_structure(data)
    assert skel["x"].shape == (2, 3)
    out = ops.initialize_tensors(skel)
    assert out["x"].shape == (2, 3)
    assert (out["x"] == 0).all()


def test_find_batch_size():
    assert ops.find_batch_size({"a": np.ones((5, 2))}) == 5
    assert ops.find_batch_size([np.ones((3,))]) == 3
    assert ops.find_batch_size({"a": 1}) is None


def test_slice_and_concat():
    data = {"a": np.arange(10)}
    sliced = ops.slice_tensors(data, slice(0, 4))
    assert sliced["a"].tolist() == [0, 1, 2, 3]
    merged = ops.concatenate([sliced, sliced])
    assert merged["a"].shape == (8,)


def test_convert_to_fp32():
    data = {"a": jnp.ones(2, dtype=jnp.bfloat16), "b": np.ones(2, dtype=np.int32)}
    out = ops.convert_to_fp32(data)
    assert out["a"].dtype == jnp.float32
    assert out["b"].dtype == np.int32  # non-float untouched


def test_gather_single_process():
    x = np.ones((4, 2))
    assert ops.gather(x) is x


def test_gather_object_single_process():
    assert ops.gather_object([1, 2]) == [1, 2]
    assert ops.gather_object("a") == ["a"]


def test_broadcast_single_process():
    x = np.ones(3)
    assert ops.broadcast(x) is x


def test_reduce_single_process():
    out = ops.reduce({"a": np.ones(2)}, reduction="sum")
    assert out["a"].tolist() == [1.0, 1.0]


def test_pad_input_tensors():
    out = ops.pad_input_tensors(np.arange(10).reshape(10, 1), batch_size=10, num_processes=4)
    assert out.shape == (12, 1)
    # duplicated head samples
    assert out[10, 0] == 0 and out[11, 0] == 0


def test_listify():
    assert ops.listify({"a": np.arange(3)}) == {"a": [0, 1, 2]}


# ---------------------------------------------------------------------------
# In-jit collectives over the 8-device mesh (shard_map plane)
# ---------------------------------------------------------------------------


def test_psum_over_mesh(mesh8):
    from shard_map_compat import shard_map

    x = jnp.arange(8.0)

    def body(x):
        return collectives.psum(x, "dp_shard")

    f = shard_map(body, mesh=mesh8, in_specs=P("dp_shard"), out_specs=P("dp_shard"))
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, np.arange(8.0).sum()))


def test_all_gather_over_mesh(mesh8):
    from shard_map_compat import NO_CHECK, shard_map

    x = jnp.arange(8.0)

    def body(x):
        return collectives.all_gather(x, "dp_shard", axis=0, tiled=True)

    f = shard_map(body, mesh=mesh8, in_specs=P("dp_shard"), out_specs=P(None), **NO_CHECK)
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_ring_permute(mesh8):
    from shard_map_compat import shard_map

    x = jnp.arange(8.0)

    def body(x):
        return collectives.ring_permute(x, "dp_shard", shift=1)

    f = shard_map(body, mesh=mesh8, in_specs=P("dp_shard"), out_specs=P("dp_shard"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_reduce_scatter(mesh8):
    from shard_map_compat import shard_map

    x = jnp.ones((64, 8))

    def body(x):
        # local block is (8, 8); scatter dim 0 splits it 8-ways after the sum
        return collectives.reduce_scatter(x, "dp_shard", axis=0)

    f = shard_map(body, mesh=mesh8, in_specs=P("dp_shard", None), out_specs=P("dp_shard", None))
    out = f(x)
    assert out.shape == (8, 8)
    # every element is the sum over the 8 shards' ones → 8.0
    np.testing.assert_allclose(np.asarray(out), np.full((8, 8), 8.0))


def test_all_to_all(mesh8):
    from shard_map_compat import shard_map

    x = jnp.arange(64.0).reshape(8, 8)

    def body(x):
        return collectives.all_to_all(x, "dp_shard", split_axis=1, concat_axis=0)

    f = shard_map(body, mesh=mesh8, in_specs=P("dp_shard", None), out_specs=P(None, "dp_shard"))
    out = f(x)
    # all_to_all transposes the sharding: result is the matrix re-tiled
    assert out.shape == (8, 8)


def test_ring_permute_larger_and_negative_shift(mesh8):
    from shard_map_compat import shard_map

    x = jnp.arange(8.0)

    def body_shift(shift):
        def body(x):
            return collectives.ring_permute(x, "dp_shard", shift=shift)

        return shard_map(body, mesh=mesh8, in_specs=P("dp_shard"), out_specs=P("dp_shard"))

    # shift=3: shard i lands on rank (i+3) % 8
    np.testing.assert_allclose(np.asarray(body_shift(3)(x)), np.roll(np.arange(8.0), 3))
    # negative shift rotates the other way around the ring
    np.testing.assert_allclose(np.asarray(body_shift(-1)(x)), np.roll(np.arange(8.0), -1))
    # a full revolution is the identity
    np.testing.assert_allclose(np.asarray(body_shift(8)(x)), np.arange(8.0))


def test_all_to_all_values(mesh8):
    from shard_map_compat import shard_map

    x = jnp.arange(64.0).reshape(8, 8)

    def body(x):
        return collectives.all_to_all(x, "dp_shard", split_axis=1, concat_axis=0)

    f = shard_map(body, mesh=mesh8, in_specs=P("dp_shard", None), out_specs=P(None, "dp_shard"))
    # the all_to_all transposes the tiling: rank j ends with every rank's
    # j-th column block — i.e. the global matrix re-tiled column-major,
    # which for the [8, 8] arange is exactly the transpose-of-blocks
    out = np.asarray(f(x))
    want = np.asarray(x).reshape(8, 8)  # block size 1x1: all_to_all == value-level identity here
    np.testing.assert_allclose(out, want)


def test_broadcast_from_nonzero_src(mesh8):
    from shard_map_compat import NO_CHECK, shard_map

    x = jnp.arange(8.0) * 10.0

    def body(src):
        def inner(x):
            return collectives.broadcast_from(x, "dp_shard", src=src)

        return shard_map(inner, mesh=mesh8, in_specs=P("dp_shard"),
                         out_specs=P("dp_shard"), **NO_CHECK)

    for src in (0, 3, 7):
        out = np.asarray(body(src)(x))
        np.testing.assert_allclose(out, np.full(8, src * 10.0))


def test_broadcast_from_rejects_out_of_range_src(mesh8):
    # the old gather-then-index form raised at trace time on a bad src; the
    # one-hot+psum rewrite must not degrade that into silent zeros
    from shard_map_compat import NO_CHECK, shard_map

    f = shard_map(
        lambda x: collectives.broadcast_from(x, "dp_shard", src=8),
        mesh=mesh8, in_specs=P("dp_shard"), out_specs=P("dp_shard"), **NO_CHECK,
    )
    with pytest.raises(ValueError, match="out of range"):
        f(jnp.arange(8.0))


def test_broadcast_from_pins_old_gather_select_behavior(mesh8):
    """The O(n) one-hot+psum broadcast must be drop-in for the previous
    all-gather-then-index implementation, including 2-D payloads and bools."""
    from shard_map_compat import NO_CHECK, shard_map
    from jax import lax

    def old_broadcast(x, axis_name, src):
        full = lax.all_gather(x, axis_name, axis=0, tiled=False)
        return full[src]

    x2d = jnp.arange(32.0).reshape(8, 4) - 7.0

    for src in (0, 5):
        new = shard_map(
            lambda x: collectives.broadcast_from(x, "dp_shard", src=src),
            mesh=mesh8, in_specs=P("dp_shard", None), out_specs=P("dp_shard", None),
            **NO_CHECK,
        )(x2d)
        old = shard_map(
            lambda x: old_broadcast(x, "dp_shard", src),
            mesh=mesh8, in_specs=P("dp_shard", None), out_specs=P("dp_shard", None),
            **NO_CHECK,
        )(x2d)
        np.testing.assert_array_equal(np.asarray(new), np.asarray(old))

    flags = jnp.asarray([True, False] * 4)
    got = shard_map(
        lambda x: collectives.broadcast_from(x, "dp_shard", src=2),
        mesh=mesh8, in_specs=P("dp_shard"), out_specs=P("dp_shard"), **NO_CHECK,
    )(flags)
    assert got.dtype == jnp.bool_
    np.testing.assert_array_equal(np.asarray(got), np.full(8, True))


def test_host_local_to_global(mesh8):
    batch = {"x": np.arange(16.0).reshape(8, 2)}
    out = ops.host_local_to_global(batch, mesh8, P("dp_shard", None))
    assert isinstance(out["x"], jax.Array)
    assert out["x"].shape == (8, 2)
    assert len(out["x"].sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(out["x"]), batch["x"])
