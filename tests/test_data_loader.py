"""Dataloader sharding-semantics tests (mirror of reference
tests/test_data_loader.py + scripts/test_distributed_data_loop.py coverage:
stride/split modes, even_batches padding, iterable sharding, skip/resume,
device placement as global sharded arrays)."""

import jax
import numpy as np
import pytest
import torch
import torch.utils.data as tud
from jax.sharding import PartitionSpec as P

from accelerate_tpu.data_loader import (
    BatchSamplerShard,
    DataLoaderDispatcher,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SkipBatchSampler,
    SkipDataLoader,
    prepare_data_loader,
    skip_first_batches,
)
from accelerate_tpu.state import GradientState, PartialState


class SimpleBatchSampler:
    def __init__(self, n, batch_size, drop_last=False):
        self.n = n
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for i in range(self.n):
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size


def _all_rank_batches(sampler_factory, num_processes, **kwargs):
    return [
        list(BatchSamplerShard(sampler_factory(), num_processes=num_processes, process_index=i, **kwargs))
        for i in range(num_processes)
    ]


def test_stride_even_division():
    # 8 samples, bs 2 -> 4 batches; 2 procs get 2 each, no padding needed
    shards = _all_rank_batches(lambda: SimpleBatchSampler(8, 2), 2)
    assert shards[0] == [[0, 1], [4, 5]]
    assert shards[1] == [[2, 3], [6, 7]]


def test_stride_uneven_even_batches_pads_from_head():
    # 10 samples, bs 2 -> 5 batches over 2 procs: rank1's last is padded
    shards = _all_rank_batches(lambda: SimpleBatchSampler(10, 2), 2)
    assert len(shards[0]) == len(shards[1]) == 3
    assert shards[0] == [[0, 1], [4, 5], [8, 9]]
    # rank 1 cycles from the head of the epoch
    assert shards[1][:2] == [[2, 3], [6, 7]]
    assert shards[1][2] == [0, 1]
    assert all(len(b) == 2 for b in shards[1])


def test_stride_short_tail_batch_padded():
    # 9 samples, bs 2 -> batches [..,[8]]: tail padded to size 2
    shards = _all_rank_batches(lambda: SimpleBatchSampler(9, 2), 2)
    assert len(shards[0]) == len(shards[1]) == 3
    for rank in shards:
        assert all(len(b) == 2 for b in rank)
    # every index is covered by the union
    union = {i for rank in shards for b in rank for i in b}
    assert union == set(range(9))


def test_stride_uneven_no_even_batches():
    shards = _all_rank_batches(lambda: SimpleBatchSampler(10, 2), 2, even_batches=False)
    assert shards[0] == [[0, 1], [4, 5], [8, 9]]
    assert shards[1] == [[2, 3], [6, 7]]


def test_stride_drop_last():
    sampler = SimpleBatchSampler(9, 2, drop_last=True)  # 4 full batches
    shards = [
        list(BatchSamplerShard(SimpleBatchSampler(9, 2, drop_last=True), num_processes=2, process_index=i))
        for i in range(2)
    ]
    assert shards[0] == [[0, 1], [4, 5]]
    assert shards[1] == [[2, 3], [6, 7]]


def test_split_batches():
    shards = _all_rank_batches(lambda: SimpleBatchSampler(8, 4), 2, split_batches=True)
    assert shards[0] == [[0, 1], [4, 5]]
    assert shards[1] == [[2, 3], [6, 7]]


def test_split_batches_tail_padded():
    shards = _all_rank_batches(lambda: SimpleBatchSampler(6, 4), 2, split_batches=True)
    assert len(shards[0]) == len(shards[1]) == 2
    assert shards[0][1] == [4, 5]
    assert shards[1][1] == [0, 1]  # padded from epoch head


def test_split_batches_requires_divisible():
    with pytest.raises(ValueError):
        BatchSamplerShard(SimpleBatchSampler(9, 3), num_processes=2, split_batches=True)


def test_iterable_dataset_shard():
    shards = [
        list(IterableDatasetShard(range(10), batch_size=2, num_processes=2, process_index=i))
        for i in range(2)
    ]
    # buffer of 4: p0 takes [0,1],[4,5]...; p1 takes [2,3],[6,7]...
    assert shards[0] == [0, 1, 4, 5, 8, 9]
    assert shards[1] == [2, 3, 6, 7, 0, 1]  # tail padded from first buffer


def test_iterable_dataset_shard_drop_last():
    shards = [
        list(IterableDatasetShard(range(10), batch_size=2, num_processes=2, process_index=i, drop_last=True))
        for i in range(2)
    ]
    assert shards[0] == [0, 1, 4, 5]
    assert shards[1] == [2, 3, 6, 7]


def test_seedable_sampler_deterministic():
    s1 = SeedableRandomSampler(10, seed=42)
    s2 = SeedableRandomSampler(10, seed=42)
    e0a, e0b = list(s1), list(s2)
    assert e0a == e0b
    e1a = list(s1)  # epoch auto-increments
    assert e1a != e0a
    s3 = SeedableRandomSampler(10, seed=42, epoch=1)
    assert list(s3) == e1a


def _torch_loader(n=16, bs=4, shuffle=False):
    data = tud.TensorDataset(torch.arange(n, dtype=torch.float32).reshape(n, 1))
    return tud.DataLoader(data, batch_size=bs, shuffle=shuffle)


def test_dataloader_shard_yields_jax_arrays():
    dl = prepare_data_loader(_torch_loader())
    batches = list(dl)
    assert len(batches) == 4
    assert isinstance(batches[0][0], jax.Array)
    np.testing.assert_allclose(np.asarray(batches[0][0]).ravel(), [0, 1, 2, 3])


def test_dataloader_shard_gradient_state_signaling():
    gs = GradientState()
    dl = prepare_data_loader(_torch_loader())
    seen_end_flags = []
    for _ in dl:
        seen_end_flags.append(gs.end_of_dataloader)
    assert seen_end_flags == [False, False, False, True]
    assert not gs.in_dataloader


def test_dataloader_shard_remainder():
    gs = GradientState()
    dl = prepare_data_loader(_torch_loader(n=10, bs=4))
    for _ in dl:
        rem = gs.remainder
    assert rem == 2


def test_dataloader_global_sharding(mesh8):
    dl = prepare_data_loader(_torch_loader(n=32, bs=8), mesh=mesh8, batch_spec=P(("dp_shard",), None))
    batch = next(iter(dl))
    x = batch[0]
    assert isinstance(x, jax.Array)
    assert len(x.sharding.device_set) == 8
    assert x.shape == (8, 1)


def test_dataloader_two_rank_simulation():
    # simulate 2 dataloader ranks in one process (reference runs subprocesses)
    dls = [
        prepare_data_loader(_torch_loader(n=16, bs=4), num_processes=2, process_index=i, put_on_device=False)
        for i in range(2)
    ]
    b0 = [np.asarray(b[0]).ravel().tolist() for b in dls[0]]
    b1 = [np.asarray(b[0]).ravel().tolist() for b in dls[1]]
    assert len(b0) == len(b1) == 2
    union = {v for batch in b0 + b1 for v in batch}
    assert union == set(float(i) for i in range(16))


def test_dataloader_total_batch_size_and_length():
    dl = prepare_data_loader(_torch_loader(n=16, bs=4))
    assert dl.total_batch_size == 4
    assert dl.total_dataset_length == 16
    assert len(dl) == 4


def test_skip_batch_sampler():
    s = SkipBatchSampler(SimpleBatchSampler(8, 2), skip_batches=2)
    assert list(s) == [[4, 5], [6, 7]]
    assert len(s) == 2


def test_skip_dataloader():
    dl = SkipDataLoader(_torch_loader(), skip_batches=2)
    batches = [np.asarray(b[0]).ravel().tolist() for b in dl]
    assert batches == [[8, 9, 10, 11], [12, 13, 14, 15]]


def test_skip_first_batches_on_prepared():
    dl = prepare_data_loader(_torch_loader())
    dl = skip_first_batches(dl, 3)
    batches = list(dl)
    assert len(batches) == 1
    np.testing.assert_allclose(np.asarray(batches[0][0]).ravel(), [12, 13, 14, 15])


def test_stateful_resume():
    dl = prepare_data_loader(_torch_loader())
    it = iter(dl)
    next(it), next(it)
    sd = dl.state_dict()
    assert sd["batches_yielded"] == 2
    dl2 = prepare_data_loader(_torch_loader())
    dl2.load_state_dict(sd)
    remaining = list(dl2)
    assert len(remaining) == 2
    np.testing.assert_allclose(np.asarray(remaining[0][0]).ravel(), [8, 9, 10, 11])


def test_stateful_resume_epoch_position_not_lifetime():
    """state_dict must record the intra-epoch position: after N full epochs
    it says 0-into-the-next-epoch, and a restored loader still yields full
    epochs (a lifetime count restored as skip would silence the loader)."""
    dl = prepare_data_loader(_torch_loader())
    for _ in range(2):
        assert len(list(dl)) == 4
    sd = dl.state_dict()
    assert sd["batches_yielded"] == 0
    assert sd["iteration"] == 2
    dl2 = prepare_data_loader(_torch_loader())
    dl2.load_state_dict(sd)
    assert len(list(dl2)) == 4


def test_stateful_resume_skip_applies_once():
    """a mid-epoch restore fast-forwards the next pass only; the epoch after
    that starts from batch 0 again."""
    dl = prepare_data_loader(_torch_loader())
    it = iter(dl)
    next(it), next(it), next(it)
    sd = dl.state_dict()
    assert sd["batches_yielded"] == 3
    dl2 = prepare_data_loader(_torch_loader())
    dl2.load_state_dict(sd)
    assert len(list(dl2)) == 1   # finishes the restored epoch
    assert len(list(dl2)) == 4   # next epoch is complete again
    # a state_dict taken right after restore (before iterating) still
    # reports the restored position
    dl3 = prepare_data_loader(_torch_loader())
    dl3.load_state_dict(sd)
    assert dl3.state_dict()["batches_yielded"] == 3
    # consuming the pass's last batch rolls the recorded position to the
    # next epoch's start — restoring THAT must not skip anything
    it3 = iter(dl3)
    next(it3)
    sd3 = dl3.state_dict()
    assert (sd3["batches_yielded"], sd3["iteration"]) == (0, 1)


def test_dispatcher_single_process():
    dl = DataLoaderDispatcher(_torch_loader(n=8, bs=4))
    batches = [np.asarray(b[0]).ravel().tolist() for b in dl]
    assert batches == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_seedable_via_prepare():
    dl = prepare_data_loader(_torch_loader(shuffle=True), use_seedable_sampler=True, data_seed=7)
    a = [np.asarray(b[0]).ravel().tolist() for b in dl]
    dl2 = prepare_data_loader(_torch_loader(shuffle=True), use_seedable_sampler=True, data_seed=7)
    b = [np.asarray(x[0]).ravel().tolist() for x in dl2]
    assert a == b  # deterministic across constructions
    flat = sorted(v for batch in a for v in batch)
    assert flat == [float(i) for i in range(16)]


def test_dataloader_parallelism_rank_collapse():
    from accelerate_tpu.parallelism_config import ParallelismConfig

    # single process: non-dp collapse must be a no-op, not a crash
    cfg = ParallelismConfig(dp_shard_size=4, tp_size=2)
    dl = prepare_data_loader(_torch_loader(), parallelism_config=cfg)
    assert len(list(dl)) == 4


def test_partial_batch_pads_to_device_multiple():
    """Device-level even_batches: a final partial batch that doesn't divide
    the dp mesh size is padded by cycling head samples (and laid out as a
    global array instead of crashing); even_batches=False surfaces the
    layout error."""
    import jax
    from jax.sharding import PartitionSpec as P

    from accelerate_tpu.parallelism_config import ParallelismConfig

    mesh = ParallelismConfig(dp_shard_size=8).build_device_mesh()
    spec = lambda x: P(("dp_shard",)) if getattr(x, "ndim", 0) >= 1 else P()
    # 13 samples, batch 8 -> final batch of 5 (not divisible by 8)
    import torch.utils.data as tud

    class _DS(tud.Dataset):
        def __len__(self):
            return 13

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    dl = DataLoaderShard(tud.DataLoader(_DS(), batch_size=8), mesh=mesh, batch_spec=spec)
    batches = list(dl)
    assert batches[0]["x"].shape == (8,)
    assert batches[1]["x"].shape == (8,)  # 5 real + 3 cycled
    pad = np.asarray(batches[1]["x"])
    assert pad[:5].tolist() == [8.0, 9.0, 10.0, 11.0, 12.0]
    assert pad[5:].tolist() == [8.0, 9.0, 10.0]  # cycled from the batch head

    dl_strict = DataLoaderShard(
        tud.DataLoader(_DS(), batch_size=8), mesh=mesh, batch_spec=spec, even_batches=False
    )
    import pytest as _pytest

    with _pytest.raises(Exception):
        list(dl_strict)


# ---------------------------------------------------------------------------
# per-host sharding of the global batch (multi-process launch contract)
# ---------------------------------------------------------------------------


def _launch_mesh():
    from accelerate_tpu.parallelism_config import ParallelismConfig

    return ParallelismConfig(dcn_size=2, dp_shard_size=4).build_device_mesh()


def test_batch_rows_process_disjoint_coverage():
    """The sharding-derived row blocks of hypothetical process groups
    (contiguous device groups, the launch topology) are disjoint, contiguous
    and cover the whole global batch — at BOTH a 2-process and a 4-process
    split of the same mesh (the elastic invariant: any process count
    re-partitions the same stream identically)."""
    from accelerate_tpu.data_loader import _rows_union, batch_rows_by_device

    mesh = _launch_mesh()
    spec = P(("dcn", "dp_replicate", "dp_shard"))
    rows = batch_rows_by_device(mesh, spec, (16, 3))
    devs = list(mesh.devices.flat)
    for nproc in (2, 4):
        per = len(devs) // nproc
        blocks = [
            _rows_union([rows[d] for d in devs[g * per:(g + 1) * per]], f"g{g}")
            for g in range(nproc)
        ]
        assert blocks[0][0] == 0 and blocks[-1][1] == 16
        for a, b in zip(blocks, blocks[1:]):
            assert a[1] == b[0], blocks  # disjoint + gap-free


def test_process_local_rows_single_process_full_block():
    from accelerate_tpu.data_loader import process_local_rows

    mesh = _launch_mesh()
    sl = process_local_rows(mesh, P(("dcn", "dp_replicate", "dp_shard")), (16, 3))
    assert (sl.start, sl.stop) == (0, 16)
    # a replicated batch dim (tp-only spec) owns the whole batch everywhere
    sl2 = process_local_rows(mesh, P(None), (16, 3))
    assert (sl2.start, sl2.stop) == (0, 16)


def test_shard_global_batch_roundtrip_and_values():
    from accelerate_tpu.data_loader import shard_global_batch

    mesh = _launch_mesh()
    spec = lambda x: P(("dcn", "dp_replicate", "dp_shard")) if x.ndim else P()
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = shard_global_batch({"x": x}, mesh, spec)["x"]
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # each device holds exactly its sharding-assigned rows
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data),
                                      x[shard.index[0]])


def test_process_sharded_loader_resume_exact_in_global_batches():
    """The shard_across_processes loader counts its resume position in
    GLOBAL batches: a mid-epoch state_dict restores to the exact next
    global batch — the process-count-independent coordinate that makes an
    elastic resume land on the same stream position at any gang size."""
    mesh = _launch_mesh()
    spec = lambda x: P(("dcn", "dp_replicate", "dp_shard")) if x.ndim else P()
    stream = [{"x": np.full((16, 3), float(i), np.float32)} for i in range(6)]

    def loader():
        return DataLoaderShard(list(stream), mesh=mesh, batch_spec=spec,
                               shard_across_processes=True)

    dl = loader()
    it = iter(dl)
    seen = [float(np.asarray(next(it)["x"])[0, 0]) for _ in range(3)]
    assert seen == [0.0, 1.0, 2.0]
    sd = dl.state_dict()
    assert sd["batches_yielded"] == 3

    dl2 = loader()
    dl2.load_state_dict(sd)
    rest = [float(np.asarray(b["x"])[0, 0]) for b in dl2]
    assert rest == [3.0, 4.0, 5.0]


def test_prepare_data_loader_auto_shard_flag():
    """Auto resolution: generic iterables get shard_across_processes only in
    multi-process worlds; torch loaders never do (BatchSamplerShard already
    sharded at the sampler)."""
    from accelerate_tpu.data_loader import prepare_data_loader

    mesh = _launch_mesh()
    spec = lambda x: P(("dcn", "dp_shard")) if getattr(x, "ndim", 0) else P()
    # single-process world: off (slicing would be identity anyway)
    dl = prepare_data_loader([{"x": np.zeros((16,), np.float32)}],
                             mesh=mesh, batch_spec=spec)
    assert isinstance(dl, DataLoaderShard) and not dl.shard_across_processes
    # explicit opt-in survives
    dl2 = prepare_data_loader([{"x": np.zeros((16,), np.float32)}],
                              mesh=mesh, batch_spec=spec,
                              shard_across_processes=True)
    assert dl2.shard_across_processes

    class _DS(tud.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return {"x": np.float32(i)}

    tl = prepare_data_loader(tud.DataLoader(_DS(), batch_size=4),
                             num_processes=2, process_index=0,
                             mesh=mesh, batch_spec=spec,
                             shard_across_processes=True)
    assert isinstance(tl, DataLoaderShard) and not tl.shard_across_processes


def test_rows_union_rejects_non_contiguous():
    from accelerate_tpu.data_loader import _rows_union

    with pytest.raises(ValueError, match="non-contiguous"):
        _rows_union([(0, 4), (8, 12)], "probe")
