"""Example scripts run end-to-end (reference tests/test_examples.py — the
feature examples are executed, not just diffed; SURVEY §4)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow  # subprocess example launches, minutes

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"


def _run(script, *extra, timeout=420):
    env = {k: v for k, v in os.environ.items() if not k.startswith("ACCELERATE_")}
    env["PYTHONPATH"] = str(REPO)
    cmd = [
        sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", "launch",
        "--cpu", "--num_cpu_devices", "4", str(script), *extra,
    ]
    result = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO
    )
    assert result.returncode == 0, f"{script}:\n{result.stdout}\n{result.stderr}"
    return result.stdout


def test_nlp_example():
    out = _run(EXAMPLES / "nlp_example.py", "--num_epochs", "2")
    assert "accuracy" in out
    acc = float(out.strip().splitlines()[-1].rsplit("accuracy ", 1)[1].split()[0])
    assert acc > 0.8, out  # signal-token task is nearly separable


def test_cv_example():
    out = _run(EXAMPLES / "cv_example.py", "--num_epochs", "1")
    assert "loss" in out


def test_complete_cv_example(tmp_path):
    out = _run(
        EXAMPLES / "complete_cv_example.py", "--num_epochs", "2",
        "--with_tracking", "--checkpointing_steps", "epoch",
        "--project_dir", str(tmp_path / "run"),
    )
    assert "accuracy" in out
    resumed = _run(
        EXAMPLES / "complete_cv_example.py", "--num_epochs", "3",
        "--resume_from_checkpoint", "--checkpointing_steps", "never",
        "--project_dir", str(tmp_path / "run"),
    )
    assert "resumed at epoch 2" in resumed


def test_complete_nlp_example(tmp_path):
    """The canonical full-featured script: every composed feature active in
    one run (tracking, epoch checkpointing, accumulation, schedule, mixed
    precision, gathered metrics), then a resume run from its checkpoints."""
    out = _run(
        EXAMPLES / "complete_nlp_example.py", "--num_epochs", "2",
        "--with_tracking", "--checkpointing_steps", "epoch",
        "--gradient_accumulation_steps", "2",
        "--project_dir", str(tmp_path / "run"),
    )
    assert "accuracy" in out
    resumed = _run(
        EXAMPLES / "complete_nlp_example.py", "--num_epochs", "3",
        "--resume_from_checkpoint", "--checkpointing_steps", "never",
        "--gradient_accumulation_steps", "2",  # epoch accounting needs the
        "--project_dir", str(tmp_path / "run"),  # same loader batch size
    )
    assert "resumed at epoch 2" in resumed and "accuracy" in resumed


@pytest.mark.parametrize(
    "script,needle",
    [
        ("checkpointing.py", "resumed fine"),
        ("gradient_accumulation.py", "loss"),
        ("tracking.py", "logged"),
        ("profiler.py", "profile traced steps"),
        ("memory.py", "attempted batch sizes [128, 64, 32]"),
        ("local_sgd.py", "final loss"),
        ("pipeline_inference.py", "pipeline over 2 stage(s)"),
        ("generation.py", "generated (2, 16) tokens"),
        ("early_stopping.py", "stopped at epoch"),
        ("multi_process_metrics.py", "eval on exactly 77 samples"),
        ("automatic_gradient_accumulation.py", "physical batch 16 x 4 accumulation"),
        ("cross_validation.py", "4-fold mse"),
        ("schedule_free.py", "schedule-free averaged params"),
        ("fsdp_with_peak_mem_tracking.py", "q_proj sharding"),
        ("gradient_accumulation_for_autoregressive_models.py", "max param diff"),
        ("grad_comm_compression.py", "bf16 gradient collectives"),
        ("zero_offload.py", "targets 2, 3"),
        ("fp8_training.py", "fp8 matmuls, bf16 activations"),
        ("bf16_master_sr.py", "x smaller with SR"),
    ],
)
def test_by_feature_examples(script, needle):
    out = _run(EXAMPLES / "by_feature" / script)
    assert needle in out, out
