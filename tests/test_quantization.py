"""Weight-only quantization tests (reference tests/test_quantization.py
capability surface: 8/4-bit load, skip-module rules, dequant matmul
accuracy, memory footprint)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.utils.quantization import (
    QuantizationConfig,
    QuantizedTensor,
    dequantize,
    dequantize_tree,
    is_quantized,
    load_and_quantize_model,
    quantize,
    quantize_params,
    quantized_apply,
    quantized_nbytes,
)


def _weight(shape=(128, 64), seed=0, scale=0.02):
    return (np.random.default_rng(seed).standard_normal(shape) * scale).astype(np.float32)


def test_int8_roundtrip_accuracy():
    w = _weight()
    qt = quantize(w, QuantizationConfig(load_in_8bit=True))
    back = np.asarray(dequantize(qt, jnp.float32))
    assert back.shape == w.shape
    # blockwise absmax int8: relative error well under 1%
    rel = np.abs(back - w).max() / np.abs(w).max()
    assert rel < 0.01, rel


def test_quantize_placement_gate():
    """The on-device fast path engages only for accelerator-backed arrays:
    host/numpy-backed inputs must not be jit-committed to the default device
    (which would transiently stage the full-precision leaf in HBM)."""
    from accelerate_tpu.utils.quantization import _accelerator_backed

    w = _weight()
    assert not _accelerator_backed(w)  # numpy
    if jax.default_backend() == "cpu":
        assert not _accelerator_backed(jnp.asarray(w))  # CPU-device jax.Array
    # explicit opt-out works regardless of placement
    qt = quantize(jnp.asarray(w), QuantizationConfig(load_in_8bit=True), on_device=False)
    assert isinstance(qt.data, np.ndarray) or not isinstance(qt.data, jax.Array)
    back = np.asarray(dequantize(qt, jnp.float32))
    assert np.abs(back - w).max() / np.abs(w).max() < 0.01


def test_nf4_roundtrip_accuracy():
    w = _weight()
    qt = quantize(w, QuantizationConfig(load_in_4bit=True))
    back = np.asarray(dequantize(qt, jnp.float32))
    assert back.shape == w.shape
    rel = np.abs(back - w).max() / np.abs(w).max()
    assert rel < 0.15, rel  # 4-bit: coarse but bounded
    # normalized codes must hit the NF4 grid exactly at block maxima
    assert np.abs(back).max() <= np.abs(w).max() * 1.0001


def test_quantized_tensor_is_pytree_and_jit_traceable():
    w = _weight((64, 64))
    qt = quantize(w, QuantizationConfig(load_in_8bit=True))
    leaves = jax.tree_util.tree_leaves(qt)
    assert len(leaves) == 2  # data + scale

    @jax.jit
    def matmul(q, x):
        return x @ dequantize(q, jnp.float32)

    x = np.ones((4, 64), np.float32)
    out = np.asarray(matmul(qt, x))
    ref = x @ np.asarray(dequantize(qt, jnp.float32))
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_quantize_params_skips_norms_and_small_leaves():
    params = {
        "layers_0": {"kernel": _weight((128, 128)), "bias": np.zeros(128, np.float32)},
        "final_norm": {"scale_w": _weight((128, 128))},  # matches 'norm' path
        "tiny": {"kernel": _weight((4, 4))},
        "embedder": {"embedding": _weight((256, 64))},
    }
    q = quantize_params(params, QuantizationConfig(load_in_8bit=True))
    assert is_quantized(q["layers_0"]["kernel"])
    assert not is_quantized(q["layers_0"]["bias"])
    assert not is_quantized(q["final_norm"]["scale_w"])
    assert not is_quantized(q["tiny"]["kernel"])
    assert not is_quantized(q["embedder"]["embedding"])
    assert quantized_nbytes(q) < quantized_nbytes(params)


def test_quantized_apply_trains_model_forward():
    """A real flax model forward under int8 weights stays close to fp32."""
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    qparams = quantize_params(params, QuantizationConfig(load_in_8bit=True, min_size=1024))

    ref = np.asarray(model.apply(params, ids))
    out = np.asarray(quantized_apply(model.apply, jnp.float32)(qparams, ids))
    assert out.shape == ref.shape
    # logits drift bounded (weight-only 8-bit)
    assert np.mean(np.abs(out - ref)) < 0.1 * (np.mean(np.abs(ref)) + 1e-6)


def test_load_and_quantize_model_streams(tmp_path):
    from accelerate_tpu.checkpointing import save_model

    class _Acc:  # minimal accelerator stub for save_model
        is_main_process = True

        @staticmethod
        def wait_for_everyone():
            pass

    params = {"block": {"kernel": _weight((128, 128)), "bias": np.zeros(128, np.float32)}}
    save_model(_Acc(), params, str(tmp_path))

    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params
    )
    q = load_and_quantize_model(abstract, str(tmp_path), QuantizationConfig(load_in_4bit=True))
    assert is_quantized(q["block"]["kernel"])
    assert isinstance(q["block"]["kernel"].data, jax.Array)
    deq = dequantize_tree(q, jnp.float32)
    rel = np.abs(np.asarray(deq["block"]["kernel"]) - params["block"]["kernel"]).max()
    assert rel < 0.15 * np.abs(params["block"]["kernel"]).max() + 1e-6


def test_load_and_quantize_model_preserves_k2d_layout(tmp_path):
    """int8 streaming load keeps the kernel-ready k2d layout through the
    device_put re-wrap — dropping it corrupts dequantization on non-square
    shapes (r2 review finding)."""
    from accelerate_tpu.checkpointing import save_model

    class _Acc:
        is_main_process = True

        @staticmethod
        def wait_for_everyone():
            pass

    W = _weight((64, 128))
    params = {"block": {"kernel": W}}
    save_model(_Acc(), params, str(tmp_path))
    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params
    )
    q = load_and_quantize_model(abstract, str(tmp_path), QuantizationConfig(load_in_8bit=True))
    qt = q["block"]["kernel"]
    assert is_quantized(qt) and qt.layout == "k2d"
    deq = np.asarray(dequantize_tree(q, jnp.float32)["block"]["kernel"])
    assert np.abs(deq - W).max() < 0.05 * np.abs(W).max() + 1e-6


def test_config_validation():
    with pytest.raises(ValueError):
        QuantizationConfig()
    with pytest.raises(ValueError):
        QuantizationConfig(load_in_8bit=True, load_in_4bit=True)


def test_odd_sized_leaf_pads_and_restores():
    w = _weight((7, 13))  # 91 elements, not a multiple of block 64
    qt = quantize(w, QuantizationConfig(load_in_8bit=True, min_size=1))
    back = np.asarray(dequantize(qt, jnp.float32))
    assert back.shape == (7, 13)
    assert np.abs(back - w).max() < 0.01 * np.abs(w).max() + 1e-6


def test_layerwise_casting_fp8_storage():
    """reference attach_layerwise_casting_hooks big_modeling.py:654 analog."""
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.ops.precision import layerwise_casting

    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    cast, wrap = layerwise_casting(params, jnp.float8_e4m3fn, jnp.float32)

    leaves = jax.tree_util.tree_flatten_with_path(cast)[0]
    stored_fp8 = [p for p, l in leaves if l.dtype == jnp.float8_e4m3fn]
    kept = [p for p, l in leaves if l.dtype == jnp.float32]
    assert stored_fp8 and kept  # projections shrank, norms/embeddings didn't

    out = np.asarray(jax.jit(wrap(model.apply))(cast, ids))
    ref = np.asarray(model.apply(params, ids))
    assert out.shape == ref.shape
    assert np.mean(np.abs(out - ref)) < 0.25 * (np.mean(np.abs(ref)) + 1e-6)
