"""The measurement harnesses in benchmarks/ back every number in the docs
(benchmarks/README.md maps each doc figure to its script); these smokes pin
that the CPU-runnable ones stay executable — the TPU-only paths are gated
inside the scripts themselves."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(args):
    # inherit the full environment (HOME, JAX/XLA vars, any rig-specific
    # site dirs ride along via PYTHONPATH) and prepend the repo root so the
    # subprocess imports THIS checkout — portable across machines/CI,
    # unlike a hardcoded site path with a stripped env
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    out = subprocess.run(
        [sys.executable, *args], cwd=REPO, capture_output=True, text=True,
        timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_sr_quality_harness_runs():
    rep = _run(["benchmarks/sr_quality.py", "--cpu", "--steps", "4",
                "--eval-every", "2", "--optimizer", "adamw-sr"])
    assert rep["metric"] == "sr_quality_shuffled_stream"
    assert rep["sr"]["optimizer"] == "adamw-sr" and rep["ref"]["optimizer"] == "adamw"
    assert rep["final_held_out_gap_pct"] is not None
    # smoke mode reports the EFFECTIVE config, not the requested TPU model
    assert rep["model"] == "tiny-cpu" and rep["backend"] == "cpu"


@pytest.mark.slow
def test_sr_quality_harness_runs_sr8():
    rep = _run(["benchmarks/sr_quality.py", "--cpu", "--steps", "4",
                "--eval-every", "2", "--optimizer", "lion-sr8"])
    assert rep["sr"]["optimizer"] == "lion-sr8" and rep["ref"]["optimizer"] == "lion"
    assert rep["final_held_out_gap_pct"] is not None


@pytest.mark.slow
def test_bench_streaming_pipeline_smoke():
    """Tiny-CPU smoke of the double-buffered offload streaming pipeline:
    bench.py --offload with a chunk budget small enough to force multiple
    groups runs end-to-end with the pipeline on, and the report ALWAYS
    carries the overlap-accounting fields (overlap_frac/h2d_bytes/d2h_bytes)
    so BENCH_*.json tracks them across rounds."""
    rep = _run(["bench.py", "--iters", "2", "--batch", "8", "--offload",
                "--chunk-gib", "1e-6", "--pipeline", "on"])
    extra = rep["extra"]
    for field in ("overlap_frac", "h2d_bytes", "d2h_bytes"):
        assert field in extra, field
    assert extra["h2d_bytes"] > 0 and extra["d2h_bytes"] > 0
    assert extra["host_update_pipeline"] is True
    assert extra["streaming"]["kind"] == "predicted"

    # the serialized A/B baseline reports zero overlap, same fields
    rep_off = _run(["bench.py", "--iters", "2", "--batch", "8", "--offload",
                    "--chunk-gib", "1e-6", "--pipeline", "off"])
    assert rep_off["extra"]["overlap_frac"] == 0.0
    assert rep_off["extra"]["streaming"]["kind"] == "serialized-baseline"

    # non-offload runs still emit the fields (zeros — nothing streams)
    rep_res = _run(["bench.py", "--iters", "2", "--batch", "8"])
    assert rep_res["extra"]["overlap_frac"] == 0.0
    assert rep_res["extra"]["h2d_bytes"] == 0
    assert rep_res["extra"]["d2h_bytes"] == 0


@pytest.mark.slow
def test_bench_collective_matmul_flag():
    """CPU-tiny smoke of ``--collective-matmul on|off``: the report ALWAYS
    carries ``tp_overlap_frac`` next to ``overlap_frac`` (0.0 on this
    bench's dp-only mesh — the TP axis is trivial) and echoes the ring
    state in ``extra`` so BENCH_*.json can track A/B runs across rounds."""
    rep_on = _run(["bench.py", "--iters", "2", "--batch", "8",
                   "--collective-matmul", "on"])
    extra = rep_on["extra"]
    assert extra["collective_matmul"] == "ring"
    assert extra["tp_overlap_frac"] == 0.0  # dp-only mesh: trivial tp axis
    assert "overlap_frac" in extra  # rides alongside the streaming fields

    rep_off = _run(["bench.py", "--iters", "2", "--batch", "8",
                    "--collective-matmul", "off"])
    assert rep_off["extra"]["collective_matmul"] == "off"
    assert rep_off["extra"]["tp_overlap_frac"] == 0.0

    # the field is present even when the flag is never passed
    rep_default = _run(["bench.py", "--iters", "2", "--batch", "8"])
    assert rep_default["extra"]["tp_overlap_frac"] == 0.0
    assert rep_default["extra"]["collective_matmul"] == "off"

    # loss parity: the ring cannot change this mesh's numbers (trivial tp
    # axis -> both runs take the identical XLA path)
    assert rep_on["extra"]["loss"] == rep_off["extra"]["loss"]


@pytest.mark.slow
def test_bench_resilience_fields_always_emitted():
    """The resilience counters ride EVERY bench report (the CI contract for
    BENCH_*.json cross-round tracking): nan_skips/restarts at zero and
    goodput_frac at 1.0 when the run is clean, with the full measured
    digest under extra["goodput"]."""
    rep = _run(["bench.py", "--iters", "2", "--batch", "8"])
    extra = rep["extra"]
    assert extra["nan_skips"] == 0
    assert extra["restarts"] == 0
    assert extra["goodput_frac"] == 1.0
    goodput = extra["goodput"]
    assert goodput["kind"] == "measured"
    assert goodput["steps"] > 0 and goodput["preemptions"] == 0
    # recompile-guard twins ride EVERY train report: after the warmup step
    # the steady-state loop predicts zero compiles, and a clean run measures
    # exactly that (the zeros-clean contract)
    assert extra["compiles_predicted"] == 0
    assert extra["compiles_measured"] == extra["compiles_predicted"] == 0

    # the fields ride the offload flavor too (next to the streaming fields)
    rep_off = _run(["bench.py", "--iters", "2", "--batch", "8", "--offload",
                    "--chunk-gib", "1e-6"])
    for field in ("nan_skips", "restarts", "goodput_frac", "overlap_frac"):
        assert field in rep_off["extra"], field


@pytest.mark.slow
def test_bench_serve_smoke():
    """CPU-tiny smoke of ``--serve`` (the serving-core traffic replay): the
    report ALWAYS carries the serving fields — tokens/s/chip, p50/p99
    per-token latency, KV-pool utilization (measured + predicted twin),
    padding-waste fraction, scheduler occupancy — and on the seeded replay
    trace continuous batching beats the static-batching twin on padding
    waste and scheduled-token efficiency (the CPU-measurable acceptance
    proxies)."""
    rep = _run(["bench.py", "--serve", "--batch", "8"])
    assert rep["metric"] == "serving_tokens_per_sec_per_chip"
    extra = rep["extra"]
    for field in ("tokens_per_sec_per_chip", "p50_token_latency_ms",
                  "p99_token_latency_ms", "kv_pool_utilization",
                  "kv_pool_utilization_predicted", "padding_waste_frac",
                  "scheduled_token_efficiency", "scheduler_occupancy",
                  "evictions", "static_baseline", "kv_pool",
                  "kv_dtype", "kv_pool_capacity_ladder",
                  "fp8_amax_history_len"):
        assert field in extra, field
    # quantized-KV fields ride every serve report zeros-clean: bf16 pool
    # by default, the capacity ladder always present (pure arithmetic),
    # the quant twin idle
    assert extra["kv_dtype"] == "bf16"
    assert extra["kv_pool_capacity_ladder"]["bf16"] == 1.0
    assert extra["kv_pool_capacity_ladder"]["int8"] > 1.5
    assert extra["fp8_amax_history_len"] == 0
    assert extra["twins"]["kv_quant.page_bytes"]["status"] == "idle"
    assert extra["completed"] == extra["requests"] > 0
    assert extra["tokens_per_sec_per_chip"] > 0
    assert extra["kv_pool_utilization"] > 0
    static = extra["static_baseline"]
    assert extra["padding_waste_frac"] < static["padding_waste_frac"]
    assert extra["scheduled_token_efficiency"] > static["scheduled_token_efficiency"]
    # the predicted KV-HBM ladder rides every serve report
    assert extra["kv_pool"]["bytes_per_page"] > 0
    assert "v5e_16GiB" in extra["kv_pool"]["hbm_frac"]
    # the seeded replay's recompile-guard twins: warmup compiles every
    # fixed-shape program up front, then the replay measures ZERO compile
    # events — compiles_measured == compiles_predicted pins that no
    # mid-traffic recompile fired (the harness raises if one does)
    assert extra["compiles_predicted"] == 0
    assert extra["compiles_measured"] == extra["compiles_predicted"] == 0
    assert extra["programs_predicted"] == len(extra["prefill_buckets"]) + 3

    # the multi-tenant adapter fields ride EVERY serve report, zeros-clean
    # when no adapters are configured (the always-emitted contract)
    for field in ("adapters", "adapter_requests", "adapter_pool_hit_rate",
                  "adapter_pool_hit_rate_predicted", "adapter_swaps",
                  "adapter_swap_bytes", "per_adapter_loop",
                  "batched_speedup_vs_loop", "adapter_pool"):
        assert field in extra, field
    assert extra["adapters"] == 0
    assert extra["adapter_pool_hit_rate"] == 0.0
    assert extra["adapter_swap_bytes"] == 0
    assert extra["per_adapter_loop"]["groups"] == 0
    assert extra["batched_speedup_vs_loop"] == 0.0
    assert extra["adapter_pool"]["pool_slots"] == 0

    # the overload-control block rides EVERY serve report, zeros-clean on a
    # clean replay (ISSUE 14: sheds/misses/cancels zero, request goodput
    # 1.0, no transfer retries, ladder at normal) — with the serving.*
    # twin rows pinned to the clean-run model
    for field in ("requests_shed", "deadline_misses", "cancelled",
                  "pages_reclaimed_on_cancel", "request_goodput_frac",
                  "transfer_retries", "ladder_stage", "ladder_engagements"):
        assert field in extra, field
    assert extra["requests_shed"] == extra["deadline_misses"] == 0
    assert extra["cancelled"] == extra["pages_reclaimed_on_cancel"] == 0
    assert extra["request_goodput_frac"] == 1.0
    assert extra["transfer_retries"] == 0
    assert extra["ladder_stage"] == "normal"
    assert extra["ladder_engagements"] == 0
    for name in ("serving.requests_shed", "serving.deadline_misses",
                 "serving.cancelled", "serving.pages_reclaimed_on_cancel",
                 "serving.request_goodput_frac"):
        row = extra["twins"][name]
        assert row["status"] == "ok", (name, row)

    # the speculative-decode fields ride EVERY serve report, zeros-clean
    # with speculation off — tokens_per_step sits exactly at the plain-
    # decode 1.0 floor a speculative run must beat
    for field in ("speculate", "speculate_k", "accept_rate",
                  "accept_rate_predicted", "tokens_per_step",
                  "tokens_per_step_predicted", "draft_overhead_frac",
                  "speculative_rollbacks", "verify_steps"):
        assert field in extra, field
    assert extra["speculate"] == "off" and extra["speculate_k"] == 0
    assert extra["accept_rate"] == 0.0
    assert extra["tokens_per_step"] == 1.0
    assert extra["draft_overhead_frac"] == 0.0
    assert extra["speculative_rollbacks"] == 0

    # the prefix-cache + disaggregation block rides EVERY serve report,
    # zeros-clean with the cache off and no transport attached (ISSUE 15:
    # the always-emitted idle contract)
    for field in ("prefix_cache", "prefix_hit_rate",
                  "prefix_hit_rate_predicted", "pages_shared_peak",
                  "cow_forks", "prefill_tokens_skipped", "prefix_evictions",
                  "page_transfers", "page_transfer_bytes", "ttft_p50_ticks",
                  "disaggregated"):
        assert field in extra, field
    assert extra["prefix_cache"] == "off"
    assert extra["prefix_hit_rate"] == 0.0
    assert extra["pages_shared_peak"] == 0 and extra["cow_forks"] == 0
    assert extra["prefill_tokens_skipped"] == 0
    assert extra["page_transfer_bytes"] == 0
    assert extra["disaggregated"]["page_transfers"] == 0
    assert extra["twins"]["prefix_cache.hit_rate"]["status"] == "idle"
    assert extra["twins"]["transfer.page_bytes"]["status"] == "idle"

    # the fleet block rides EVERY serve report, zeros-clean without
    # --fleet (ISSUE 19: the always-emitted contract — no replicas, no
    # routing, parity vacuously true)
    fleet = extra["fleet"]
    for field in ("replicas", "alive", "policy", "requests", "completed",
                  "goodput_frac", "ttft_p50_ticks", "prefix_hit_rate",
                  "adapter_pool_hit_rate", "page_transfer_bytes",
                  "compiles_warmup_by_role", "compiles_measured",
                  "routed_by_prefix", "routed_by_adapter", "routed_by_load",
                  "drain_events", "per_replica", "token_parity_vs_fused"):
        assert field in fleet, field
    assert fleet["replicas"] == fleet["alive"] == 0
    assert fleet["goodput_frac"] == 0.0
    assert fleet["page_transfer_bytes"] == 0
    assert fleet["compiles_measured"] == 0
    assert fleet["routed_by_prefix"] == fleet["routed_by_adapter"] == 0
    assert fleet["drain_events"] == [] and fleet["per_replica"] == []
    assert fleet["token_parity_vs_fused"] is True

    # idle trace: every field still present, zeros (the always-emitted
    # contract BENCH_*.json relies on)
    rep_idle = _run(["bench.py", "--serve", "--batch", "8",
                     "--serve-requests", "0"])
    extra_idle = rep_idle["extra"]
    assert extra_idle["tokens_per_sec_per_chip"] == 0.0
    assert extra_idle["kv_pool_utilization"] == 0.0
    assert extra_idle["padding_waste_frac"] == 0.0
    assert extra_idle["scheduler_occupancy"] == 0.0
    assert extra_idle["p50_token_latency_ms"] == 0.0
    assert extra_idle["adapters"] == 0 and extra_idle["adapter_swaps"] == 0
    assert extra_idle["tokens_per_step"] == 0.0
    assert extra_idle["accept_rate"] == 0.0
    assert extra_idle["requests_shed"] == 0 and extra_idle["cancelled"] == 0
    assert extra_idle["deadline_misses"] == 0
    assert extra_idle["request_goodput_frac"] == 0.0  # nothing served
    assert extra_idle["ladder_stage"] == "normal"


@pytest.mark.slow
def test_bench_serve_prefix_share_smoke():
    """``--serve --prefix-share``: on the seeded shared-system-prompt CPU
    trace the prefix cache must actually reuse (prefill_tokens_skipped >
    0, hit rate > 0 with the scheduler-replay predicted twin within its
    registered tolerance), continuous-with-reuse must beat no-reuse on
    TTFT (virtual ticks — deterministic on CPU), tokens stay bitwise
    identical reuse on/off, and the replay stays recompile-free; with
    ``--disaggregate`` the pair's tokens match the fused engine and
    page_transfer_bytes equals the dcn accounting model exactly."""
    rep = _run(["bench.py", "--serve", "--batch", "4", "--serve-requests",
                "10", "--prefix-share", "0.8", "--disaggregate"])
    extra = rep["extra"]
    assert extra["prefix_cache"] == "on"
    assert extra["prefix_hit_rate"] > 0.0
    assert extra["prefill_tokens_skipped"] > 0
    assert extra["prefix_reuse_token_parity"] is True
    # reuse beats no-reuse on TTFT (the acceptance comparison, in ticks)
    assert extra["ttft_p50_ticks"] < extra["ttft_no_reuse_p50_ticks"]
    row = extra["twins"]["prefix_cache.hit_rate"]
    assert row["rel_err"] <= row["tolerance"], row
    assert extra["compiles_measured"] == 0
    # the disaggregated slice: parity + the exact byte twin
    dis = extra["disaggregated"]
    assert dis["token_parity_vs_fused"] is True
    assert dis["page_transfers"] > 0
    assert dis["compiles_prefill"] == 0 and dis["compiles_decode"] == 0
    assert extra["page_transfer_bytes"] == \
        extra["transfer_accounting"]["page_transfer_bytes"] > 0
    assert extra["twins"]["transfer.page_bytes"]["rel_err"] == 0.0


@pytest.mark.slow
def test_bench_serve_fleet_smoke():
    """``--serve --fleet 2``: the same seeded trace routed across two
    replicas — merged tokens BITWISE equal to the single fused engine in
    the same report, goodput 1.0, zero post-warmup compiles per replica,
    and the shared-preamble trace actually routes by prefix affinity;
    with ``--disaggregate`` each replica is a prefill→decode pair and KV
    pages cross the wire."""
    rep = _run(["bench.py", "--serve", "--batch", "4", "--serve-requests",
                "10", "--prefix-share", "0.8", "--fleet", "2"])
    fleet = rep["extra"]["fleet"]
    assert fleet["replicas"] == fleet["alive"] == 2
    assert fleet["policy"] == "affinity"
    assert fleet["token_parity_vs_fused"] is True
    assert fleet["goodput_frac"] == 1.0
    assert fleet["completed"] == fleet["requests"] > 0
    assert fleet["compiles_measured"] == 0
    assert fleet["routed_by_prefix"] > 0
    assert len(fleet["per_replica"]) == 2

    # fleet of disaggregated pairs with adapters + speculation: the
    # previously-forbidden combination rides the split per replica
    rep2 = _run(["bench.py", "--serve", "--batch", "4", "--serve-requests",
                 "10", "--prefix-share", "0.8", "--fleet", "2",
                 "--disaggregate", "--adapters", "2", "--speculate", "2"])
    fleet2 = rep2["extra"]["fleet"]
    assert fleet2["token_parity_vs_fused"] is True
    assert fleet2["goodput_frac"] == 1.0
    assert fleet2["compiles_measured"] == 0
    assert fleet2["page_transfer_bytes"] > 0
    assert fleet2["adapter_pool_hit_rate"] > 0
    assert set(fleet2["compiles_warmup_by_role"]) >= {"prefill", "decode"}


@pytest.mark.slow
def test_bench_serve_prefix_all_armed_strict_compiles():
    """The acceptance gate: reuse + speculation + adapters ALL armed on one
    replay — strict_compiles holds post-warmup (the harness raises on any
    mid-traffic compile, so the bench completing IS the pin) and the
    prefix block still measures real reuse."""
    # 16 requests at share 0.9: tenant-keyed hashing splits the preambles
    # across 3 adapter classes, so the trace needs enough arrivals for
    # same-tenant repeats to land hits
    rep = _run(["bench.py", "--serve", "--batch", "4", "--serve-requests",
                "16", "--prefix-share", "0.9", "--speculate", "3",
                "--adapters", "2"])
    extra = rep["extra"]
    assert extra["prefix_cache"] == "on"
    assert extra["speculate"] == "ngram"
    assert extra["adapters"] > 0
    assert extra["compiles_measured"] == 0
    assert extra["prefill_tokens_skipped"] > 0
    assert extra["prefix_reuse_token_parity"] is True


@pytest.mark.slow
def test_bench_serve_speculate_smoke():
    """``--serve --speculate``: the speculative run must beat the
    speculate-off run's tokens/step (1.0, the plain-decode floor) on the
    seeded CPU trace, the accept-rate twin agrees (predicted trace replay
    vs measured) within its declared tolerance, the replay stays
    recompile-free across the verify bucket ladder, and the idle-trace
    report keeps every speculate field zeros-clean."""
    rep = _run(["bench.py", "--serve", "--batch", "8", "--speculate"])
    extra = rep["extra"]
    assert extra["speculate"] == "ngram" and extra["speculate_k"] == 4
    assert extra["tokens_per_step"] > 1.0          # beats speculate-off's 1.0
    assert extra["accept_rate"] > 0.0
    assert extra["verify_steps"] > 0
    assert extra["compiles_measured"] == 0
    # the TwinRegistry rows: registered and within the declared tolerance
    for name in ("speculate.accept_rate", "speculate.tokens_per_step"):
        row = extra["twins"][name]
        assert row["status"] in ("ok", "warn"), (name, row)
        assert row["measured"] > 0
        assert row["rel_err"] <= row["tolerance"], (name, row)
    # verify bucket programs join the predicted program set
    assert extra["programs_predicted"] == len(extra["prefill_buckets"]) + 3 + 1
    # idle trace with speculation armed: zeros-clean
    rep_idle = _run(["bench.py", "--serve", "--batch", "8", "--speculate",
                     "--serve-requests", "0"])
    ei = rep_idle["extra"]
    assert ei["accept_rate"] == ei["tokens_per_step"] == 0.0
    assert ei["draft_overhead_frac"] == 0.0 and ei["speculative_rollbacks"] == 0


@pytest.mark.slow
def test_bench_serve_adapters_smoke():
    """``--serve --adapters N`` (multi-tenant batched LoRA): the adapter
    fields measure real traffic — hot-swaps happen (the pool is undersized
    on purpose), the predicted/measured hit-rate twins agree on the seeded
    trace, the pool ladder rides along, the replay stays recompile-free for
    the mixed tenant set, and the batched einsum beats the per-adapter-loop
    twin on tokens/s (the acceptance criterion's CPU proxy)."""
    rep = _run(["bench.py", "--serve", "--batch", "8", "--adapters", "3"])
    extra = rep["extra"]
    assert extra["adapters"] == 3
    assert extra["adapter_requests"] > 0
    assert extra["adapter_swaps"] > 0
    assert extra["adapter_swap_bytes"] > 0
    assert 0.0 < extra["adapter_pool_hit_rate"] <= 1.0
    # the LRU-replay predicted twin tracks the measured rate (divergence =
    # in-flight pinning/eviction reorder, bounded on the seeded trace)
    assert abs(extra["adapter_pool_hit_rate"]
               - extra["adapter_pool_hit_rate_predicted"]) < 0.3
    assert extra["adapter_pool"]["pool_bytes"] > 0
    assert extra["adapter_pool"]["swap_s_pred"] > 0
    # one fixed-shape program set for ANY tenant mix: zero post-warmup
    # compiles even with hot-swaps mid-traffic
    assert extra["compiles_measured"] == 0
    # the S-LoRA win: batched multi-adapter decode beats serving the same
    # trace one tenant at a time
    assert extra["per_adapter_loop"]["groups"] > 1
    assert extra["batched_speedup_vs_loop"] > 1.0


@pytest.mark.slow
def test_bench_plan_audit_hook():
    """``--plan N --audit`` embeds the graft-lint jaxpr-audit summary for
    the selected step: a tiny train step traced through the real
    prepare_train_step machinery with the selected optimizer (pure
    abstract trace — CPU-safe, nothing executes on device)."""
    rep = _run(["bench.py", "--plan", "8", "--batch", "8", "--audit"])
    audit = rep["extra"]["audit"]
    assert audit["ok"] is True
    assert audit["error"] == 0 and audit["warning"] == 0
    assert "rules" in audit and "suppressed" in audit
    # the compiled twin rides next to the trace audit: the same canonical
    # step AOT-compiled and audited at the executable level (GL301-303),
    # with the per-program cost row the predicted-MFU math feeds on
    compiled = rep["extra"]["compiled_audit"]
    assert compiled["ok"] is True and compiled["error"] == 0
    assert len(compiled["programs"]) == 1
    prog = compiled["programs"][0]
    assert prog["hbm"]["total"] > 0 and prog["flops"] > 0
    assert prog["aliased_bytes"] > 0  # the donated state actually aliased

    # audit rides along on the inference plan flavor too
    rep_inf = _run(["bench.py", "--plan", "8", "--batch", "8",
                    "--plan-task", "infer", "--audit"])
    assert rep_inf["extra"]["audit"]["ok"] is True

    # without --audit the plan stays audit-free (no accidental cost)
    rep_plain = _run(["bench.py", "--plan", "8", "--batch", "8"])
    assert "audit" not in rep_plain["extra"]


@pytest.mark.slow
def test_host_compute_probe_quiet_box_gate():
    """The probe enforces the quiet-box precondition and carries the gate
    report (loadavg + calibration vs the 1.71 GiB/s baseline) in its JSON;
    on a loaded box it refuses without --force.  CPU backends run the same
    chain with the baseline comparison non-binding.  --force here: loadavg
    is host-wide, so a busy CI box would otherwise flip the refusal path
    and flake this smoke — the gate report is emitted either way, which is
    what the assertions pin."""
    rep = _run(["benchmarks/host_compute_probe.py", "--gib", "0.05", "--force"])
    gate = rep["quiet_box"]
    assert "load" in gate and "calibration" in gate
    assert gate["baseline_gibs"] == 1.71
    assert gate["calibration"]["gibs"] > 0
    assert rep["aggregate_gib_s"] > 0


@pytest.mark.slow
def test_t131k_probe_cpu_components_run():
    # matmul + offload skeleton run on any backend (--cpu forces the CPU
    # backend even under the axon sitecustomize); flash needs the TPU
    for comp in ("matmul", "offload"):
        rep = _run(["benchmarks/t131k_probe.py", "--seq-len", "512",
                    "--component", comp, "--cpu"])
        assert rep["component"] == comp and "value" in rep


@pytest.mark.slow
def test_bench_dcn_fields_always_emitted():
    """dcn_bytes / dcn_bytes_flat / dcn_overlap_frac ride EVERY train report
    (the always-emitted-twins contract): zeros-clean on a mesh without a
    dcn axis, and populated — with the hierarchical schedule strictly under
    the flat twin, PowerSGD under the dense slab — in both --dcn-compress
    states on a simulated 2-slice mesh."""
    # no dcn axis: fields present, zeros-clean
    rep = _run(["bench.py", "--iters", "2", "--batch", "8"])
    extra = rep["extra"]
    assert extra["dcn_bytes"] == 0 and extra["dcn_bytes_flat"] == 0
    assert extra["dcn_overlap_frac"] == 0.0
    assert extra["dcn_comm"]["hierarchical"] is False

    # 2-slice mesh, dense DCN hop (--dcn-compress off)
    rep_dense = _run(["bench.py", "--iters", "2", "--batch", "8",
                      "--dcn-slices", "2", "--dcn-compress", "off"])
    dense = rep_dense["extra"]
    assert dense["dcn_comm"]["hierarchical"] is True
    assert dense["dcn_comm"]["compression"] is None
    assert 0 < dense["dcn_bytes"] < dense["dcn_bytes_flat"]
    assert 0.0 <= dense["dcn_overlap_frac"] <= 1.0

    # PowerSGD DCN codec (--dcn-compress on): strictly fewer bytes again
    rep_c = _run(["bench.py", "--iters", "2", "--batch", "8",
                  "--dcn-slices", "2", "--dcn-compress", "on"])
    comp = rep_c["extra"]
    assert comp["dcn_comm"]["compression"] == "powersgd"
    assert 0 < comp["dcn_bytes"] < dense["dcn_bytes"]
    assert comp["dcn_bytes_flat"] == dense["dcn_bytes_flat"]


STANDARD_TWIN_NAMES = (
    "offload_transfer.overlap_frac", "tp_comm.overlap_frac",
    "dcn_comm.dcn_bytes", "kv_pool.utilization", "adapter_pool.hit_rate",
    "goodput.goodput_frac", "compiles.steady_state",
)


@pytest.mark.slow
def test_bench_telemetry_fields_always_emitted():
    """schema_version / telemetry_overhead_frac / the unified twins block
    ride EVERY bench report (train, serve and idle flavors), zeros-clean
    when nothing recorded — the always-emitted contract plus the canonical
    seven twin rows with per-twin rel_err and drift status."""
    rep = _run(["bench.py", "--iters", "2", "--batch", "8"])
    extra = rep["extra"]
    assert extra["schema_version"] == 1
    assert extra["telemetry_overhead_frac"] == 0.0  # telemetry off: free
    twins = extra["twins"]
    for name in STANDARD_TWIN_NAMES:
        assert name in twins, name
        row = twins[name]
        assert set(row) >= {"predicted", "measured", "rel_err", "status",
                            "units", "tolerance"}, row
        assert row["status"] in ("idle", "ok", "warn", "error")
    # the clean train run: goodput + compiles twins agree exactly
    assert twins["goodput.goodput_frac"]["status"] == "ok"
    assert twins["compiles.steady_state"]["rel_err"] == 0.0
    # subsystems the run never touched stay zeros-clean idle rows
    assert twins["kv_pool.utilization"]["status"] == "idle"
    assert twins["kv_pool.utilization"]["measured"] == 0.0

    # --telemetry on: the timeline summary + a measured overhead fraction,
    # and the loss is bitwise identical to the telemetry-off run
    rep_t = _run(["bench.py", "--iters", "2", "--batch", "8",
                  "--telemetry", "on"])
    extra_t = rep_t["extra"]
    assert extra_t["timeline"]["step_dispatch"]["count"] > 0
    assert 0.0 <= extra_t["telemetry_overhead_frac"] < 0.5
    assert extra_t["loss"] == extra["loss"]

    # serve flavor: same contract, kv-pool twin populated by the replay
    rep_s = _run(["bench.py", "--serve", "--batch", "8"])
    extra_s = rep_s["extra"]
    assert extra_s["schema_version"] == 1
    assert extra_s["telemetry_overhead_frac"] == 0.0  # tracing off
    assert extra_s["trace_spans"] == 0
    s_twins = extra_s["twins"]
    for name in STANDARD_TWIN_NAMES:
        assert name in s_twins, name
    assert s_twins["kv_pool.utilization"]["measured"] > 0
    assert s_twins["compiles.steady_state"]["status"] == "ok"


@pytest.mark.slow
def test_bench_serve_trace_requests(tmp_path):
    """--serve --trace-requests FILE: the exported Chrome trace validates,
    spans were recorded, overhead is measured, and the serving numbers
    (tokens, schedule, compiles) are identical to the untraced run of the
    same seeded trace (telemetry is bitwise-invisible)."""
    from accelerate_tpu.telemetry import validate_chrome_trace

    trace_file = str(tmp_path / "serve_trace.json")
    rep = _run(["bench.py", "--serve", "--batch", "8",
                "--trace-requests", trace_file])
    extra = rep["extra"]
    assert extra["trace_spans"] > 0
    assert extra["telemetry_overhead_frac"] > 0.0
    assert extra["trace_file"] == trace_file
    chrome = json.loads(Path(trace_file).read_text())
    assert validate_chrome_trace(chrome) == []
    names = {e["name"] for e in chrome["traceEvents"] if e["ph"] != "M"}
    assert {"submit", "queued", "admit", "prefill_chunk", "retire",
            "schedule", "host_sync"} <= names
    # tracing never compiled a program mid-replay (strict_compiles held)
    assert extra["compiles_measured"] == 0

    rep_off = _run(["bench.py", "--serve", "--batch", "8"])
    # same seeded trace, identical serving outcome fields
    for field in ("generated_tokens", "prompt_tokens", "engine_steps",
                  "decode_steps", "prefill_steps", "evictions", "completed"):
        assert extra[field] == rep_off["extra"][field], field


@pytest.mark.slow
def test_bench_fp8_smoke():
    """``--fp8`` (shorthand for --precision fp8): the train bench runs the
    delayed-scaling recipe end to end on CPU — loss finite, the amax
    history window reported (the always-emitted field), and the
    steady-state recompile guard still green (the delayed-scaling state
    update must not re-key the jit cache between steps)."""
    rep = _run(["bench.py", "--fp8", "--iters", "2", "--batch", "8",
                "--no-selftest"])
    extra = rep["extra"]
    assert extra["precision"] == "fp8"
    assert extra["fp8_amax_history_len"] >= 1
    assert extra["loss"] > 0
    assert extra["twins"]["compiles.steady_state"]["status"] == "ok"

    # bf16 default: the fp8 field still rides the report, zeros-clean
    rep_bf16 = _run(["bench.py", "--iters", "2", "--batch", "8",
                     "--no-selftest"])
    assert rep_bf16["extra"]["precision"] == "bf16"
    assert rep_bf16["extra"]["fp8_amax_history_len"] == 0


@pytest.mark.slow
def test_bench_serve_kv_quant_smoke():
    """``--serve --kv-dtype int8``: the quantized KV page pool serves the
    seeded trace end to end — strict_compiles holds (warmup compiles every
    program, the replay then measures ZERO compile events over quantized
    pages), the kv_quant.page_bytes twin is EXACT (allocated pool arrays
    vs the kv_page_bytes model, tolerance 0.0), and the capacity ladder
    reports the quantized pool's token-capacity multiple."""
    rep = _run(["bench.py", "--serve", "--batch", "8", "--kv-dtype", "int8"])
    extra = rep["extra"]
    assert extra["kv_dtype"] == "int8"
    assert extra["completed"] == extra["requests"] > 0
    assert extra["tokens_per_sec_per_chip"] > 0
    assert extra["compiles_measured"] == 0  # strict_compiles over int8 pages
    row = extra["twins"]["kv_quant.page_bytes"]
    assert row["status"] == "ok" and row["rel_err"] == 0.0, row
    assert row["predicted"] == row["measured"] > 0
    assert extra["kv_pool"]["kv_dtype"] == "int8"
    assert extra["kv_pool"]["capacity_vs_bf16"] > 1.5
    assert extra["kv_pool_capacity_ladder"]["int8"] == \
        extra["kv_pool"]["capacity_vs_bf16"]


@pytest.mark.slow
def test_bench_serve_kv_quant_disaggregate_transfer_twin():
    """``--serve --kv-dtype int8 --disaggregate``: quantized pages travel
    the prefill→decode wire (codes + per-page scales), the pair's greedy
    tokens match the fused engine BITWISE, and the transfer.page_bytes
    twin is exact at the roughly-halved quantized wire unit."""
    rep = _run(["bench.py", "--serve", "--batch", "4", "--serve-requests",
                "6", "--kv-dtype", "int8", "--disaggregate"])
    extra = rep["extra"]
    assert extra["disaggregated"]["token_parity_vs_fused"] is True
    row = extra["twins"]["transfer.page_bytes"]
    assert row["status"] == "ok" and row["predicted"] == row["measured"] > 0
    # the quantized wire unit is well under the bf16 one for this geometry
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.serving.paged_cache import kv_page_bytes

    cfg = LlamaConfig.tiny()
    page_size = 4  # the CPU-tiny serve geometry bench.py pins
    assert extra["transfer_accounting"]["bytes_per_page"] == \
        kv_page_bytes(cfg, page_size, 2, "int8")
    assert kv_page_bytes(cfg, page_size, 2, "int8") < \
        kv_page_bytes(cfg, page_size, 2)


@pytest.mark.slow
def test_fp8_quality_harness_runs():
    """The fp8-vs-bf16 loss-envelope harness (benchmarks/fp8_quality.py,
    the sr_quality.py discipline): identical Zipf stream, held-out batch,
    both envelope numbers emitted.  The documented 240-step envelope
    (docs/performance.md) comes from the full run; this smoke pins the
    harness stays executable."""
    rep = _run(["benchmarks/fp8_quality.py", "--cpu", "--steps", "4",
                "--eval-every", "2"])
    assert rep["metric"] == "fp8_quality_shuffled_stream"
    assert rep["scaling"] == "delayed"
    assert rep["model"] == "tiny-cpu" and rep["backend"] == "cpu"
    assert rep["final_held_out_gap_pct"] is not None
    assert rep["train_envelope_max_pct"] >= 0.0
