"""The measurement harnesses in benchmarks/ back every number in the docs
(benchmarks/README.md maps each doc figure to its script); these smokes pin
that the CPU-runnable ones stay executable — the TPU-only paths are gated
inside the scripts themselves."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(args):
    # inherit the full environment (HOME, JAX/XLA vars, any rig-specific
    # site dirs ride along via PYTHONPATH) and prepend the repo root so the
    # subprocess imports THIS checkout — portable across machines/CI,
    # unlike a hardcoded site path with a stripped env
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    out = subprocess.run(
        [sys.executable, *args], cwd=REPO, capture_output=True, text=True,
        timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_sr_quality_harness_runs():
    rep = _run(["benchmarks/sr_quality.py", "--cpu", "--steps", "4",
                "--eval-every", "2", "--optimizer", "adamw-sr"])
    assert rep["metric"] == "sr_quality_shuffled_stream"
    assert rep["sr"]["optimizer"] == "adamw-sr" and rep["ref"]["optimizer"] == "adamw"
    assert rep["final_held_out_gap_pct"] is not None
    # smoke mode reports the EFFECTIVE config, not the requested TPU model
    assert rep["model"] == "tiny-cpu" and rep["backend"] == "cpu"


@pytest.mark.slow
def test_sr_quality_harness_runs_sr8():
    rep = _run(["benchmarks/sr_quality.py", "--cpu", "--steps", "4",
                "--eval-every", "2", "--optimizer", "lion-sr8"])
    assert rep["sr"]["optimizer"] == "lion-sr8" and rep["ref"]["optimizer"] == "lion"
    assert rep["final_held_out_gap_pct"] is not None


@pytest.mark.slow
def test_t131k_probe_cpu_components_run():
    # matmul + offload skeleton run on any backend (--cpu forces the CPU
    # backend even under the axon sitecustomize); flash needs the TPU
    for comp in ("matmul", "offload"):
        rep = _run(["benchmarks/t131k_probe.py", "--seq-len", "512",
                    "--component", comp, "--cpu"])
        assert rep["component"] == comp and "value" in rep
