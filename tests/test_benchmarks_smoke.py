"""The measurement harnesses in benchmarks/ back every number in the docs
(benchmarks/README.md maps each doc figure to its script); these smokes pin
that the CPU-runnable ones stay executable — the TPU-only paths are gated
inside the scripts themselves."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(args):
    out = subprocess.run(
        [sys.executable, *args], cwd=REPO, capture_output=True, text=True,
        timeout=600,
        env={"PYTHONPATH": f"{REPO}:/root/.axon_site", "PATH": "/usr/bin:/bin:/usr/local/bin"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_sr_quality_harness_runs():
    rep = _run(["benchmarks/sr_quality.py", "--cpu", "--steps", "4",
                "--eval-every", "2", "--optimizer", "adamw-sr"])
    assert rep["metric"] == "sr_quality_shuffled_stream"
    assert rep["sr"]["optimizer"] == "adamw-sr" and rep["ref"]["optimizer"] == "adamw"
    assert rep["final_held_out_gap_pct"] is not None


@pytest.mark.slow
def test_t131k_probe_cpu_components_run():
    # matmul + offload skeleton run on any backend (--cpu forces the CPU
    # backend even under the axon sitecustomize); flash needs the TPU
    for comp in ("matmul", "offload"):
        rep = _run(["benchmarks/t131k_probe.py", "--seq-len", "512",
                    "--component", comp, "--cpu"])
        assert rep["component"] == comp and "value" in rep
