"""Prefix-cached paged KV tests: copy-on-write shared pages, refcounted
eviction, and the first disaggregated prefill→decode slice (ISSUE 15).

The acceptance pins: ``generate_paged`` greedy tokens are BITWISE identical
with prefix caching on or off — including under eviction/recompute
pressure, speculative-decode rollback, mixed LoRA tenant traffic, and
cancel/deadline/prefix-flush chaos — the refcounted
``verify_serving_invariants`` contract holds after every scenario (no
referenced page on the free stack, refcounts balance the index + slot
holds exactly, host shared-prefix mirror == device block-table rows), and
the disaggregated pair emits the same tokens as a fused engine with the
``transfer.page_bytes`` twin exact.

Every engine in this module shares ONE geometry (slots=4, page=4, pool=24,
chunk=8 — test_overload.py's) so the process-shared jit cache compiles
each program exactly once across both modules (the tier-1 time budget).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate, generate_paged
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.resilience import FaultEvent, FaultPlan
from accelerate_tpu.serving import (
    DisaggregatedPair,
    PrefixCache,
    Request,
    ServingEngine,
    block_hashes,
    chaos_replay,
    prefix_cache_accounting,
    replay,
    synthesize_trace,
    transfer_accounting,
    verify_serving_invariants,
)
from accelerate_tpu.telemetry import twin_registry
from accelerate_tpu.utils.dataclasses import ServingPlugin

MAX_NEW = 16  # ONE decode budget for the module: every engine shares jits


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _plugin(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("decode_kernel", "native")
    return ServingPlugin(**kw)


def _engine(tiny_model, **kw):
    model, params = tiny_model
    return ServingEngine(model, params, _plugin(**kw),
                         GenerationConfig(max_new_tokens=MAX_NEW))


def _shared_trace(seed, n, share=0.85, pre_len=9, new=(4, 8)):
    return synthesize_trace(
        seed, n, vocab_size=256, mean_interarrival_steps=1.0,
        prompt_len_range=(4, 12), new_tokens_range=new,
        prefix_share=share, shared_prefix_len=pre_len,
    )


def _assert_clean(eng):
    problems = verify_serving_invariants(eng)
    assert not problems, problems


# ---------------------------------------------------------------------------
# host-side contracts: hashing, refcounts, LRU, the double-free guard
# ---------------------------------------------------------------------------


def test_block_hash_chain_cap_and_tenant_keying():
    """Hashes chain (a page's hash commits to the WHOLE prefix), cap at
    (len-1)//page so the last prompt token always prefills, and the tenant
    id keys the chain (cross-tenant prompts never alias)."""
    p = tuple(range(1, 14))  # 13 tokens, page 4 -> cap (13-1)//4 = 3 pages
    h = block_hashes(p, 4)
    assert len(h) == 3
    # page-aligned prompt: the last page is still not cacheable
    assert len(block_hashes(tuple(range(1, 13)), 4)) == 2  # 12 tokens
    assert len(block_hashes((1, 2, 3), 4)) == 0
    # chaining: same page-2 tokens under a different page-1 differ
    q = (99,) + p[1:]
    assert block_hashes(q, 4)[1] != h[1]
    # tenant keying
    assert block_hashes(p, 4, adapter_id=1) != h


def test_refcount_lifecycle_reclaim_lru_and_protect():
    pc = PrefixCache(4)
    h = block_hashes(tuple(range(1, 14)), 4)
    # a prefilled slot inserts its pages: index hold + slot hold each
    assert pc.insert_owned(h, [10, 11, 12]) == [10, 11, 12]
    assert pc.refcount == {10: 2, 11: 2, 12: 2}
    # a second admission adopts the full prefix
    assert pc.adopt(h) == [10, 11, 12]
    assert pc.refcount[10] == 3
    assert pc.stats["pages_shared_peak"] == 3
    # nothing reclaimable while slots hold references
    pc.unref_pages([10, 11, 12])          # second slot releases
    assert pc.reclaim_one() is None        # first slot still holds
    assert pc.unref_pages([10, 11, 12]) == 0  # index still holds all three
    # now index-only: LRU reclaim frees, protect exempts
    assert pc.reclaim_one(protect=frozenset({10, 11, 12})) is None
    page = pc.reclaim_one()
    assert page == 10                      # LRU: earliest-touched first
    assert pc.pop_pending() == [10]
    assert pc.flush() == 2                 # the remaining index-only pages
    assert sorted(pc.pop_pending()) == [11, 12]
    assert pc.refcount == {} and pc.index == {}


def test_pop_pending_double_free_guard_planted():
    """THE corruption a refcount bug causes: a still-referenced page queued
    for the device free stack must fail loudly at the host boundary."""
    pc = PrefixCache(4)
    pc.ref_pages([7])
    pc.pending_free.append(7)  # planted: freed while referenced
    with pytest.raises(RuntimeError, match="double-free"):
        pc.pop_pending()


def test_insert_stops_at_indexed_conflict():
    """A concurrent identical prefill that lost the race keeps its
    duplicate pages private — every slot's shared set stays a contiguous
    block-table row prefix."""
    pc = PrefixCache(4)
    h = block_hashes(tuple(range(1, 14)), 4)
    pc.insert_owned(h[:2], [3, 4])
    # the loser tries to insert the same chain with ITS pages: nothing lands
    assert pc.insert_owned(h, [20, 21, 22]) == []
    assert pc.index[h[0]] == 3 and 20 not in pc.refcount
    # a disjoint continuation past the indexed prefix does land
    assert pc.insert_owned(h[2:], [22]) == [22]


def test_prefix_cache_accounting_envelope():
    trace = _shared_trace(0, 8)
    acc = prefix_cache_accounting(LlamaConfig.tiny(), trace, 4, dtype_bytes=4)
    assert acc["cacheable_pages_total"] >= acc["cacheable_pages_unique"] > 0
    assert 0.0 < acc["dedup_frac"] < 1.0
    assert acc["prefill_tokens_skippable"] > 0
    assert 0.0 < acc["hit_rate_upper"] <= 1.0
    assert acc["shared_bytes_peak_upper"] == \
        acc["cacheable_pages_unique"] * acc["bytes_per_page"]


# ---------------------------------------------------------------------------
# THE acceptance pins: bitwise parity with reuse on/off
# ---------------------------------------------------------------------------


def test_generate_paged_bitwise_prefix_on_off(tiny_model):
    model, params = tiny_model
    rng = np.random.default_rng(1)
    pre = tuple(int(x) for x in rng.integers(1, 255, 9))
    prompts = [pre + tuple(int(x) for x in rng.integers(1, 255, k))
               for k in (3, 5, 4)]
    width = max(len(p) for p in prompts)
    ids = np.zeros((3, width), np.int32)
    lens = []
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
        lens.append(len(p))
    gc = GenerationConfig(max_new_tokens=MAX_NEW)
    plug = _plugin(num_slots=3)
    off = generate_paged(model, params, ids, gc, prompt_lengths=lens,
                         serving_plugin=plug)
    on = generate_paged(model, params, ids, gc, prompt_lengths=lens,
                        serving_plugin=plug, prefix_cache=True)
    ref = generate(model, params, jnp.asarray(ids), gc,
                   prompt_lengths=jnp.asarray(lens))
    assert np.array_equal(np.asarray(on), np.asarray(off))
    assert np.array_equal(np.asarray(on), np.asarray(ref))


def test_eviction_pressure_parity_hits_and_invariants(tiny_model):
    """Recompute-on-readmit under pool pressure: reuse changes WHERE K/V
    comes from, never the tokens; LRU reclaim fires (index-only pages are
    cheaper capacity than any live sequence) and the refcounted
    conservation contract holds after the storm."""
    trace = _shared_trace(7, 12, new=(4, 10))
    res = {}
    for mode in ("off", "on"):
        eng = _engine(tiny_model, num_pages=24, prefix_cache=mode)
        rep = replay(eng, trace, verify_invariants=True)
        res[mode] = (rep["results"], rep)
        _assert_clean(eng)
    on_rep = res["on"][1]
    assert res["on"][0] == res["off"][0]
    assert on_rep["prefix_hit_rate"] > 0.0
    assert on_rep["prefill_tokens_skipped"] > 0
    assert on_rep["pages_shared_peak"] > 0
    assert on_rep["compiles_measured"] == 0


def test_speculative_rollback_never_frees_aliased_page(tiny_model):
    """Speculation + prefix reuse composed: the verify pass's worst-case
    allocate → rollback cycle only ever touches pages popped THIS pass
    (always private by construction), so tokens stay bitwise and the
    refcounted invariants hold with both armed."""
    trace = _shared_trace(7, 10, new=(4, 10))
    res = {}
    for mode in ("off", "on"):
        eng = _engine(tiny_model, num_pages=24, prefix_cache=mode,
                      speculate="ngram", speculate_k=4)
        rep = replay(eng, trace, verify_invariants=True)
        res[mode] = rep
        _assert_clean(eng)
    assert res["on"]["results"] == res["off"]["results"]
    assert res["on"]["verify_steps"] > 0
    assert res["on"]["compiles_measured"] == 0
    # plain engine equality too: speculation is already pinned bitwise
    base = _engine(tiny_model, num_pages=24)
    assert replay(base, trace)["results"] == res["on"]["results"]


def test_mixed_lora_tenants_never_alias_and_stay_bitwise(tiny_model):
    """The hash chain is keyed by adapter_id: two tenants sending the SAME
    prompt must not share pages (their K/V differ under their adapters),
    and the multi-tenant serve stays bitwise with reuse on."""
    import tempfile

    from accelerate_tpu.serving import AdapterStore
    from accelerate_tpu.utils.dataclasses import LoraPlugin

    model, params = tiny_model
    trace = synthesize_trace(
        11, 10, vocab_size=256, mean_interarrival_steps=1.0,
        prompt_len_range=(4, 10), new_tokens_range=(3, 6), adapters=2,
        prefix_share=0.9, shared_prefix_len=9,
    )
    res = {}
    for mode in ("off", "on"):
        with tempfile.TemporaryDirectory() as d:
            store = AdapterStore(
                params, LoraPlugin(rank=4, pool_slots=2, kernel="native"),
                dtype=model.config.dtype, offload_dir=d,
            )
            for t in (1, 2):
                store.publish_random(t, jax.random.PRNGKey(1000 + t))
            eng = ServingEngine(model, params, _plugin(prefix_cache=mode),
                                GenerationConfig(max_new_tokens=MAX_NEW),
                                adapters=store)
            rep = replay(eng, trace, verify_invariants=True)
            res[mode] = rep
            _assert_clean(eng)
    assert res["on"]["results"] == res["off"]["results"]
    # cross-tenant isolation: the same preamble under different tenants
    # hashes to different chains, so any page every tenant hit is its own
    pc = PrefixCache(4)
    pre = trace[0].prompt[:8]
    assert pc.block_hashes(pre, 1) != pc.block_hashes(pre, 2)


def test_chaos_prefix_fault_interplay(tiny_model):
    """The chaos soak extended with the ``prefix`` fault (an index flush
    mid-traffic) interleaved with cancel + deadline storms: survivors'
    tokens BITWISE equal a fault-free replay of the same surviving set,
    zero post-warmup compiles, refcounted invariants green after every
    engine life."""
    model, params = tiny_model
    plug = _plugin(prefix_cache="on")
    gc = GenerationConfig(max_new_tokens=MAX_NEW)
    trace = _shared_trace(9, 10, new=(4, 8))
    engines = []

    def factory():
        eng = ServingEngine(model, params, plug, gc)
        engines.append(eng)
        return eng

    plan = FaultPlan([
        FaultEvent("prefix", at=6),
        FaultEvent("cancel", at=12),
        FaultEvent("prefix", at=18),
    ])
    rep = chaos_replay(factory, trace, plan)
    assert rep["token_parity"]
    assert rep["compiles_measured"] == 0
    assert not rep["invariant_problems"]
    assert rep["completed"] > 0
    flushes = [e for eng in engines for e in eng.sched.events
               if e[0] == "prefix_flush"]
    assert flushes, "the prefix fault never flushed the index"


def test_invariant_checker_detects_planted_refcount_corruption(tiny_model):
    """The refcount-aware checker flags exactly the corruption a refcount
    bug causes: a referenced page on the free stack (double-free), a
    phantom refcount, and a diverged shared-prefix mirror."""
    eng = _engine(tiny_model, prefix_cache="on")
    trace = _shared_trace(5, 6)
    replay(eng, trace, verify_invariants=True)
    # plant 1: a still-referenced page pushed onto the device free stack
    eng.prefix.ref_pages([3])
    problems = verify_serving_invariants(eng)
    assert any("refcount" in p or "double-free" in p or "conservation" in p
               for p in problems), problems
    eng.prefix.unref_pages([3])
    eng.prefix.pending_free.clear()
    _assert_clean(eng)
    # plant 2: an undrained pending push across the tick boundary
    eng.prefix.pending_free.append(99)
    problems = verify_serving_invariants(eng)
    assert any("pending_free" in p for p in problems), problems
    eng.prefix.pending_free.clear()


def test_replay_report_prefix_fields_zeros_clean_and_twin(tiny_model):
    """The idle contract: every prefix field present and zero with the
    cache off; with it on, the scheduler-replay predicted twin agrees
    with the measured hit rate within its registered tolerance (it models
    concurrency and reclaim exactly — on a clean replay they are equal)."""
    eng = _engine(tiny_model)  # prefix off
    rep = replay(eng, [])
    for k in ("prefix_hit_rate", "prefix_hit_rate_predicted",
              "pages_shared_peak", "cow_forks", "prefill_tokens_skipped",
              "prefix_evictions", "page_transfers", "page_transfer_bytes"):
        assert rep[k] == 0, (k, rep[k])
    assert rep["prefix_cache"] == "off"
    eng = _engine(tiny_model, prefix_cache="on")
    trace = _shared_trace(3, 10)
    rep = replay(eng, trace)
    assert rep["prefix_cache"] == "on"
    assert rep["prefix_hit_rate"] > 0
    twin = twin_registry().get("prefix_cache.hit_rate")
    assert twin is not None and twin.rel_err <= twin.tolerance, twin.row()
    assert rep["cow_forks"] >= 0 and rep["ttft_p50_ticks"] > 0


def test_scheduler_determinism_includes_prefix_events(tiny_model):
    """Same seed → identical decision log, prefix_hit / cow_fork /
    prefix_evict events included (the determinism contract extends to the
    sharing machinery)."""
    trace = _shared_trace(13, 10, new=(4, 10))
    logs = []
    for _ in range(2):
        eng = _engine(tiny_model, prefix_cache="on")
        replay(eng, trace)
        logs.append(list(eng.sched.events))
    assert logs[0] == logs[1]
    kinds = {e[0] for e in logs[0]}
    assert "prefix_hit" in kinds


def test_ttft_improves_with_reuse_on_shared_trace(tiny_model):
    """The deterministic TTFT comparison (virtual ticks): reuse skips the
    shared region's prefill, so time-to-first-token on the seeded shared
    trace must not regress — and real prefill work must be saved."""
    trace = _shared_trace(7, 12, new=(4, 10))
    ticks = {}
    steps = {}
    for mode in ("off", "on"):
        eng = _engine(tiny_model, prefix_cache=mode)
        rep = replay(eng, trace)
        ticks[mode] = rep["ttft_p50_ticks"]
        steps[mode] = rep["engine_steps"]
    assert ticks["on"] <= ticks["off"]
    assert steps["on"] < steps["off"]  # skipped chunks = fewer engine ticks


# ---------------------------------------------------------------------------
# disaggregated prefill→decode
# ---------------------------------------------------------------------------


def test_disaggregated_pair_bitwise_and_transfer_twin(tiny_model):
    """The handoff slice: pair tokens BITWISE equal the fused engine's,
    page_transfer_bytes exactly matches the dcn accounting model, zero
    post-warmup compiles on either engine, invariants green on both."""
    model, params = tiny_model
    gc = GenerationConfig(max_new_tokens=MAX_NEW)
    trace = _shared_trace(15, 8, new=(3, 8))
    fused = _engine(tiny_model)
    fused_results = replay(fused, trace)["results"]
    pair = DisaggregatedPair(model, params, _plugin(), gc)
    pair.warmup()
    out = pair.run(trace)
    assert out == fused_results
    rep = pair.report()
    assert rep["compiles_prefill"] == 0 and rep["compiles_decode"] == 0
    acc = transfer_accounting(
        model.config, trace, 4,
        dtype_bytes=jnp.dtype(model.config.dtype).itemsize,
    )
    assert rep["page_transfer_bytes"] == acc["page_transfer_bytes"] > 0
    twin = twin_registry().get("transfer.page_bytes")
    assert twin.rel_err == 0.0, twin.row()
    _assert_clean(pair.prefill_engine)
    _assert_clean(pair.decode_engine)
    # the decode engine's metrics carry the wire bytes for the report
    assert pair.decode_engine.metrics["page_transfer_bytes"] == \
        rep["page_transfer_bytes"]


def test_disaggregated_pair_composes_with_prefix_cache(tiny_model):
    """Prefix reuse on the prefill engine: the transferred pages are the
    CACHED bytes — parity must hold end to end."""
    model, params = tiny_model
    gc = GenerationConfig(max_new_tokens=MAX_NEW)
    trace = _shared_trace(15, 8, new=(3, 8))
    fused = _engine(tiny_model)
    fused_results = replay(fused, trace)["results"]
    pair = DisaggregatedPair(model, params, _plugin(prefix_cache="on"), gc)
    pair.warmup()
    assert pair.run(trace) == fused_results
    assert pair.prefill_engine.prefix.stats["prefill_tokens_skipped"] > 0
    _assert_clean(pair.prefill_engine)
    _assert_clean(pair.decode_engine)


def test_pair_immune_to_default_deadline(tiny_model):
    """``submit()`` re-stamps ``default_deadline_ticks`` onto any request
    carrying 0 — the pair must disarm the DEFAULT too, or an env/plugin
    deadline silently cancels prefills mid-hold and run() returns an
    incomplete results dict (review regression)."""
    model, params = tiny_model
    gc = GenerationConfig(max_new_tokens=MAX_NEW)
    trace = _shared_trace(15, 6, new=(3, 8))
    fused = _engine(tiny_model)
    fused_results = replay(fused, trace)["results"]
    pair = DisaggregatedPair(model, params,
                             _plugin(default_deadline_ticks=2), gc)
    pair.warmup()
    out = pair.run(trace)
    assert set(out) == {r.uid for r in trace}
    assert out == fused_results


def test_held_finished_slot_never_evicted_or_cancelled(tiny_model):
    """A hold_finished (prefill-role) engine parks finished sequences with
    their pages intact until the KV transfer: page pressure, deadline
    sweeps and cancels must all pass over a held slot (review regression —
    evicting one requeues an already-finished request and orphans the
    held-slot bookkeeping)."""
    model, params = tiny_model
    rng = np.random.default_rng(23)
    eng = ServingEngine(model, params, _plugin(),
                        GenerationConfig(max_new_tokens=MAX_NEW),
                        hold_finished=True)
    eng.warmup()
    # three 21-token prompts hold 6 pages each (18 of 24) once parked; the
    # deadline expires AFTER they park (a mid-prefill expiry is a
    # legitimate cancel) — the sweep must then pass over the held slots
    for uid in range(3):
        eng.add_request(Request(
            uid=uid, prompt=tuple(int(x) for x in rng.integers(1, 255, 21)),
            max_new_tokens=1, deadline_ticks=20,
        ))
    for _ in range(100):
        if len(eng.held) == 3:
            break
        eng.step()
    assert len(eng.held) == 3
    held_uids = {eng.sched.slots[s].request.uid for s in eng.held}
    # a 28-token prompt needs 7 pages; only 6 are free — page pressure with
    # every other slot held.  The prefilling slot must cancel ITSELF rather
    # than evict a parked sequence.
    eng.add_request(Request(
        uid=9, prompt=tuple(int(x) for x in rng.integers(1, 255, 28)),
        max_new_tokens=1,
    ))
    for s in list(eng.held):
        eng.cancel(eng.sched.slots[s].request.uid)  # raced finishes: no-ops
    for _ in range(50):
        # keep stepping past tick 20 so the deadline sweep runs against
        # the (expired) held slots too
        if eng.steps > 25 and (9 in eng.sched.retired_uids
                               or 9 in eng.results):
            break
        eng.step()
    assert len(eng.held) == 3
    assert {eng.sched.slots[s].request.uid for s in eng.held} == held_uids
    assert all(e[1] not in held_uids for e in eng.sched.events
               if e[0] == "evict")
    _assert_clean(eng)
    for s in list(eng.held):
        eng.release_held(s)
    assert not eng.held and 9 not in eng.sched.slots
    _assert_clean(eng)


# ---------------------------------------------------------------------------
# plugin / env plumbing
# ---------------------------------------------------------------------------


def test_serving_plugin_prefix_knob(monkeypatch):
    assert ServingPlugin().prefix_cache == "off"
    assert ServingPlugin(prefix_cache=True).prefix_cache == "on"
    assert ServingPlugin(prefix_cache="1").prefix_cache == "on"
    monkeypatch.setenv("ACCELERATE_SERVE_PREFIX_CACHE", "on")
    assert ServingPlugin().prefix_cache == "on"
    assert ServingPlugin(prefix_cache=False).prefix_cache == "off"
    with pytest.raises(ValueError):
        ServingPlugin(prefix_cache="sideways")
