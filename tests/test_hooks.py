"""Functional hooks engine tests (reference tests/test_hooks.py surface:
hook lifecycle, sequential composition, attach/remove idempotence, device
alignment with offloaded weights, layerwise casting)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.hooks import (
    AlignDevicesHook,
    CpuOffloadHook,
    LayerwiseCastingHook,
    ModelHook,
    SequentialHook,
    add_hook_to_apply,
    attach_align_device_hook,
    remove_hook_from_apply,
)


def _apply(params, x):
    return x @ params["w"] + params["b"]


def _params():
    rng = np.random.default_rng(0)
    return {
        "w": jnp.asarray(rng.standard_normal((4, 3)), jnp.float32),
        "b": jnp.zeros(3, jnp.float32),
    }


def test_hook_pre_and_post_forward():
    calls = []

    class Scale(ModelHook):
        def pre_forward(self, params, *args, **kwargs):
            calls.append("pre")
            return jax.tree.map(lambda p: p * 2, params), args, kwargs

        def post_forward(self, params, output):
            calls.append("post")
            return output + 1

    params, x = _params(), jnp.ones((2, 4))
    wrapped = add_hook_to_apply(_apply, Scale())
    out = wrapped(params, x)
    ref = _apply(jax.tree.map(lambda p: p * 2, params), x) + 1
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    assert calls == ["pre", "post"]


def test_sequential_hook_order():
    order = []

    def mk(tag):
        class H(ModelHook):
            def pre_forward(self, params, *args, **kwargs):
                order.append(f"pre-{tag}")
                return params, args, kwargs

            def post_forward(self, params, output):
                order.append(f"post-{tag}")
                return output

        return H()

    wrapped = add_hook_to_apply(_apply, SequentialHook(mk("a"), mk("b")))
    wrapped(_params(), jnp.ones((1, 4)))
    # pre in order, post reversed (reference SequentialHook semantics)
    assert order == ["pre-a", "pre-b", "post-b", "post-a"]


def test_add_replaces_unless_append():
    class AddOne(ModelHook):
        def post_forward(self, params, output):
            return output + 1

    params, x = _params(), jnp.ones((1, 4))
    base = float(_apply(params, x).sum())
    once = add_hook_to_apply(_apply, AddOne())
    replaced = add_hook_to_apply(once, AddOne())  # replace: still +1
    appended = add_hook_to_apply(once, AddOne(), append=True)  # chain: +2
    assert float(replaced(params, x).sum()) == pytest.approx(base + 3)   # 3 outputs
    assert float(appended(params, x).sum()) == pytest.approx(base + 6)


def test_remove_hook_restores_original():
    class AddOne(ModelHook):
        def post_forward(self, params, output):
            return output + 1

    wrapped = add_hook_to_apply(_apply, AddOne())
    restored = remove_hook_from_apply(wrapped)
    assert restored is _apply
    assert remove_hook_from_apply(_apply) is _apply  # no-op without hook


def test_align_devices_hook_ships_host_params():
    params = {k: np.asarray(v) for k, v in _params().items()}  # host numpy
    wrapped = attach_align_device_hook(_apply)
    out = wrapped(params, jnp.ones((2, 4)))
    assert isinstance(out, jax.Array)
    ref = _apply({k: jnp.asarray(v) for k, v in params.items()}, jnp.ones((2, 4)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_align_devices_hook_reads_offload_store(tmp_path):
    from accelerate_tpu.big_modeling import offload_state_dict

    params = {k: np.asarray(v) for k, v in _params().items()}
    store = offload_state_dict(str(tmp_path), params)
    lazy = {k: store.load(k) for k in params}  # np.memmap leaves
    wrapped = attach_align_device_hook(_apply)
    out = wrapped(lazy, jnp.ones((2, 4)))
    ref = _apply({k: jnp.asarray(v) for k, v in params.items()}, jnp.ones((2, 4)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_cpu_offload_hook():
    wrapped = add_hook_to_apply(_apply, CpuOffloadHook())
    out = wrapped(_params(), jnp.ones((2, 4)))
    assert np.isfinite(np.asarray(out)).all()


def test_layerwise_casting_hook():
    from accelerate_tpu.ops.precision import layerwise_casting

    params = {"dense": {"kernel": jnp.asarray(np.random.default_rng(0).standard_normal((4, 3)), jnp.float32) * 0.1}}
    cast, _ = layerwise_casting(params, jnp.float8_e4m3fn, jnp.float32, skip_patterns=())

    def apply_fn(p, x):
        return x @ p["dense"]["kernel"]

    wrapped = add_hook_to_apply(apply_fn, LayerwiseCastingHook(jnp.float8_e4m3fn, jnp.float32))
    out = wrapped(cast, jnp.ones((2, 4)))
    ref = apply_fn(jax.tree.map(lambda x: x.astype(jnp.float32), cast), jnp.ones((2, 4)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
