"""Long-context attention tests: flash (interpret), ring CP (both rotate
methods, zigzag), Ulysses SP — all against the native reference on the
8-device CPU mesh (reference parity role: CP/SP correctness, SURVEY §5
'long-context')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu.models.llama import native_attention
from accelerate_tpu.ops.flash_attention import flash_attention
from accelerate_tpu.parallel.context_parallel import (
    make_ring_attention,
    zigzag_shard,
    zigzag_unshard,
)
from accelerate_tpu.parallel.sequence_parallel import make_ulysses_attention
from accelerate_tpu.parallelism_config import ParallelismConfig


def _qkv(b=2, t=32, h=4, d=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.fixture
def cp_mesh():
    return ParallelismConfig(cp_size=8).build_device_mesh()


@pytest.fixture
def sp_mesh():
    return ParallelismConfig(sp_size=4, dp_shard_size=2).build_device_mesh()


def test_flash_matches_native_interpret():
    q, k, v = _qkv()
    for causal in (True, False):
        ref = native_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grads_match_native():
    """dq AND dk/dv (both backward kernels) against the native reference."""
    q, k, v = _qkv()
    f = lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True, block_q=8, block_k=8, interpret=True) ** 2)
    g = lambda q, k, v: jnp.sum(native_attention(q, k, v, causal=True) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, err_msg=f"d{name}")


@pytest.mark.slow
def test_flash_non_divisible_seq_len():
    """Sequence lengths not divisible by the block size must still be exact
    (padded tile rows/cols are masked, not garbage): fwd + both bwd kernels."""
    rng = np.random.default_rng(3)
    B, T, H, D = 1, 12, 2, 8  # T=12 with block 8 -> padded second block
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    for causal in (True, False):
        ref = native_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    f = lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True, block_q=8, block_k=8, interpret=True) ** 2)
    g = lambda q, k, v: jnp.sum(native_attention(q, k, v, causal=True) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gn):
        assert np.all(np.isfinite(np.asarray(a))), f"d{name} has NaN/inf"
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, err_msg=f"d{name}")


def test_flash_gqa():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 16, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 16, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 16, 2, 8)), jnp.float32)
    ref = native_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("rotate", ["allgather", "alltoall"])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_native(cp_mesh, rotate, causal):
    q, k, v = _qkv(t=32)
    ref = native_attention(q, k, v, causal=causal)
    # zigzag layout: host-reorder, shard, attend, un-reorder
    qz = jnp.asarray(zigzag_shard(q, 8))
    kz = jnp.asarray(zigzag_shard(k, 8))
    vz = jnp.asarray(zigzag_shard(v, 8))
    spec = NamedSharding(cp_mesh, P(None, "cp", None, None))
    qz, kz, vz = jax.device_put(qz, spec), jax.device_put(kz, spec), jax.device_put(vz, spec)
    attn = make_ring_attention(cp_mesh, rotate_method=rotate, zigzag=True)
    out = attn(qz, kz, vz, causal=causal)
    out = zigzag_unshard(np.asarray(out), 8)
    np.testing.assert_allclose(out, np.asarray(ref), atol=1e-4)


@pytest.mark.slow
def test_ring_attention_gqa(cp_mesh):
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    ref = native_attention(q, k, v, causal=True)
    qz, kz, vz = (jnp.asarray(zigzag_shard(x, 8)) for x in (q, k, v))
    attn = make_ring_attention(cp_mesh, rotate_method="alltoall", zigzag=True)
    out = zigzag_unshard(np.asarray(attn(qz, kz, vz, causal=True)), 8)
    np.testing.assert_allclose(out, np.asarray(ref), atol=1e-4)


@pytest.mark.slow
def test_ring_attention_differentiable(cp_mesh):
    q, k, v = _qkv(t=16)
    attn = make_ring_attention(cp_mesh, rotate_method="alltoall", zigzag=False)

    def f(q):
        return jnp.sum(attn(q, k, v, causal=True) ** 2)

    def g(q):
        return jnp.sum(native_attention(q, k, v, causal=True) ** 2)

    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)), np.asarray(jax.grad(g)(q)), atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_native(sp_mesh, causal):
    q, k, v = _qkv(t=32, h=4)
    ref = native_attention(q, k, v, causal=causal)
    spec = NamedSharding(sp_mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    attn = make_ulysses_attention(sp_mesh)
    out = attn(qs, ks, vs, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_head_divisibility_error(sp_mesh):
    q, k, v = _qkv(t=32, h=3)
    attn = make_ulysses_attention(sp_mesh)
    with pytest.raises(ValueError, match="divisible"):
        attn(q, k, v)


def test_ulysses_in_jitted_train_step(sp_mesh):
    """Ulysses attention composes under jit + grad (the train-step path)."""
    q, k, v = _qkv(t=32, h=4)
    attn = make_ulysses_attention(sp_mesh)

    @jax.jit
    def loss(q, k, v):
        return jnp.sum(attn(q, k, v, causal=True) ** 2)

    g = jax.grad(loss)(q, k, v)
    assert g.shape == q.shape
    assert np.isfinite(np.asarray(g)).all()


def test_cross_rank_token_mean(sp_mesh):
    from shard_map_compat import NO_CHECK, shard_map

    from accelerate_tpu.parallel.sequence_parallel import cross_rank_token_mean

    loss = jnp.arange(32.0).reshape(1, 32)
    mask = jnp.ones((1, 32))

    def body(loss, mask):
        return cross_rank_token_mean(loss, mask, ("sp",))

    f = shard_map(body, mesh=sp_mesh, in_specs=(P(None, "sp"), P(None, "sp")),
                  out_specs=P(), **NO_CHECK)
    out = float(f(loss, mask))
    assert out == pytest.approx(float(jnp.mean(loss)))


@pytest.mark.slow
def test_flash_gqa_grads_no_repeat():
    """GQA path: dk/dv come back at kv-head shape (group-summed in-kernel)."""
    rng = np.random.default_rng(5)
    B, T, H, Hkv, D = 2, 16, 8, 2, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
    f = lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True, block_q=8, block_k=8, interpret=True) ** 2)
    g = lambda q, k, v: jnp.sum(native_attention(q, k, v, causal=True) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (B, T, Hkv, D)
    for name, a, b in zip("qkv", gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}")


@pytest.mark.slow
def test_flash_segment_ids_in_kernel():
    """Packed sequences run inside the fused kernel (no native fallback):
    cross-segment attention masked in fwd and all three grads."""
    rng = np.random.default_rng(6)
    B, T, H, Hkv, D = 2, 16, 4, 2, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
    segs = jnp.asarray(np.repeat([[0] * 6 + [1] * 10], B, axis=0), jnp.int32)
    for causal in (True, False):
        ref = native_attention(q, k, v, causal=causal, segment_ids=segs)
        out = flash_attention(q, k, v, causal=causal, segment_ids=segs, block_q=8, block_k=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)
    f = lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True, segment_ids=segs, block_q=8, block_k=8, interpret=True) ** 2)
    g = lambda q, k, v: jnp.sum(native_attention(q, k, v, causal=True, segment_ids=segs) ** 2)
    gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}")


@pytest.mark.slow
@pytest.mark.parametrize("rotate", ["allgather", "alltoall"])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_ring_matches_native(cp_mesh, rotate, causal):
    """Ring attention with per-block flash kernels (position-masked causal,
    logsumexp combine) against the native reference."""
    q, k, v = _qkv(t=32)
    ref = native_attention(q, k, v, causal=causal)
    qz, kz, vz = (jnp.asarray(zigzag_shard(x, 8)) for x in (q, k, v))
    attn = make_ring_attention(cp_mesh, rotate_method=rotate, zigzag=True, use_flash=True)
    out = zigzag_unshard(np.asarray(attn(qz, kz, vz, causal=causal)), 8)
    np.testing.assert_allclose(out, np.asarray(ref), atol=2e-4)


@pytest.mark.slow
def test_flash_ring_differentiable(cp_mesh):
    """Gradients flow through the flash blocks AND the lse combine (the
    g_lse -> delta fold in the kernel backward)."""
    q, k, v = _qkv(t=16)
    attn = make_ring_attention(cp_mesh, rotate_method="alltoall", zigzag=False, use_flash=True)
    f = lambda q: jnp.sum(attn(q, k, v, causal=True) ** 2)
    g = lambda q: jnp.sum(native_attention(q, k, v, causal=True) ** 2)
    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)), np.asarray(jax.grad(g)(q)), atol=2e-4)


def test_flash_positions_and_lse():
    """Explicit positions drive the causal mask; return_lse matches a direct
    logsumexp of the masked scores."""
    rng = np.random.default_rng(7)
    B, T, H, D = 1, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    perm = np.asarray([1, 0, 3, 2, 5, 4, 7, 6, 9, 8, 11, 10, 13, 12, 15, 14])
    pos = jnp.asarray(perm[None, :], jnp.int32)
    out, lse = flash_attention(
        q, k, v, causal=True, positions=pos, return_lse=True,
        block_q=8, block_k=8, interpret=True,
    )
    # reference with an explicit position mask
    s = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D)
    mask = pos[0][:, None] >= pos[0][None, :]
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    ref = jnp.einsum("bhts,bshd->bthd", p, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    ref_lse = jax.nn.logsumexp(s, -1).transpose(0, 2, 1)  # [B, T, H]
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=1e-4)


@pytest.mark.slow
@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_attention_gqa_no_repeat(cp_mesh, use_flash):
    """GQA KV shards travel the ring at kv-head width (no pre-repeat)."""
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.normal(size=(1, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    ref = native_attention(q, k, v, causal=True)
    qz, kz, vz = (jnp.asarray(zigzag_shard(x, 8)) for x in (q, k, v))
    attn = make_ring_attention(cp_mesh, rotate_method="alltoall", zigzag=True, use_flash=use_flash)
    out = zigzag_unshard(np.asarray(attn(qz, kz, vz, causal=True)), 8)
    np.testing.assert_allclose(out, np.asarray(ref), atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("rotate", ["allgather", "alltoall"])
@pytest.mark.parametrize("use_flash", [False, True])
def test_ring_attention_segment_ids(cp_mesh, rotate, use_flash):
    """Packed sequences under CP: segment ids rotate with KV; cross-segment
    attention masked identically to the unsharded native reference."""
    rng = np.random.default_rng(12)
    B, T, H, Hkv, D = 2, 32, 4, 2, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
    segs = jnp.asarray(np.repeat([[0] * 10 + [1] * 14 + [2] * 8], B, axis=0), jnp.int32)
    for causal in (True, False):
        ref = native_attention(q, k, v, causal=causal, segment_ids=segs)
        qz, kz, vz = (jnp.asarray(zigzag_shard(x, 8)) for x in (q, k, v))
        segz = jnp.asarray(zigzag_shard(segs, 8)) if causal else segs
        attn = make_ring_attention(cp_mesh, rotate_method=rotate, zigzag=causal, use_flash=use_flash)
        out = zigzag_unshard(np.asarray(attn(qz if causal else q, kz if causal else k,
                                             vz if causal else v, causal=causal,
                                             segment_ids=segz)), 8) if causal else \
            np.asarray(attn(q, k, v, causal=causal, segment_ids=segs))
        np.testing.assert_allclose(out, np.asarray(ref), atol=2e-4,
                                   err_msg=f"causal={causal}")


@pytest.mark.slow
def test_ring_attention_segment_ids_differentiable(cp_mesh):
    """Grads flow through the segment-masked ring path (flash in-kernel)."""
    rng = np.random.default_rng(13)
    q, k, v = _qkv(t=16, seed=13)
    segs = jnp.asarray(np.repeat([[0] * 6 + [1] * 10], 2, axis=0), jnp.int32)
    attn = make_ring_attention(cp_mesh, rotate_method="alltoall", zigzag=False, use_flash=True)
    f = lambda q: jnp.sum(attn(q, k, v, causal=True, segment_ids=segs) ** 2)
    g = lambda q: jnp.sum(native_attention(q, k, v, causal=True, segment_ids=segs) ** 2)
    np.testing.assert_allclose(np.asarray(jax.grad(f)(q)), np.asarray(jax.grad(g)(q)), atol=2e-4)


def test_flash_cross_segment_ids():
    """Distinct q/kv segment ids (the ring building block) against a masked
    reference with T != S."""
    rng = np.random.default_rng(14)
    B, T, S, H, D = 1, 8, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    seg_q = jnp.asarray([[0] * 4 + [1] * 4], jnp.int32)
    seg_kv = jnp.asarray([[0] * 10 + [1] * 6], jnp.int32)
    out = flash_attention(q, k, v, causal=False, segment_ids=seg_q, kv_segment_ids=seg_kv,
                          block_q=8, block_k=8, interpret=True)
    s = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D)
    mask = seg_q[0][:, None] == seg_kv[0][None, :]
    s = jnp.where(mask[None, None], s, -1e30)
    ref = jnp.einsum("bhts,bshd->bthd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_segment_ids(sp_mesh, causal):
    """Packed sequences under SP: local segment ids all-gather to the full
    sequence each rank attends over."""
    rng = np.random.default_rng(21)
    B, T, H, D = 2, 32, 4, 8
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    segs = jnp.asarray(np.repeat([[0] * 10 + [1] * 14 + [2] * 8], B, axis=0), jnp.int32)
    ref = native_attention(q, k, v, causal=causal, segment_ids=segs)
    attn = make_ulysses_attention(sp_mesh)
    out = attn(q, k, v, causal=causal, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ulysses_gqa_no_repeat_when_divisible(sp_mesh):
    """GQA kv heads divisible by sp travel the all_to_alls at kv width."""
    rng = np.random.default_rng(22)
    q = jnp.asarray(rng.normal(size=(1, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)  # 4 kv heads, sp=4
    v = jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)
    ref = native_attention(q, k, v, causal=True)
    out = make_ulysses_attention(sp_mesh)(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ulysses_gqa_indivisible_falls_back(sp_mesh):
    """kv heads < sp: broadcast to q width (correctness preserved)."""
    rng = np.random.default_rng(23)
    q = jnp.asarray(rng.normal(size=(1, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)  # 2 kv heads, sp=4
    v = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)
    ref = native_attention(q, k, v, causal=True)
    out = make_ulysses_attention(sp_mesh)(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_default_block_sizes_heuristic():
    """Tiling heuristic: MXU-aligned, seq-clamped, VMEM-bounded."""
    from accelerate_tpu.ops.flash_attention import _VMEM_BUDGET_BYTES, default_block_sizes

    assert default_block_sizes(2048, 2048, 96) == (1024, 1024)  # measured sweet spot
    bq, bk = default_block_sizes(12, 12, 8)
    assert bq == 128 and bk == 128  # never below one MXU tile
    bq, bk = default_block_sizes(8192, 8192, 1024)  # giant head dim must shrink
    assert 4 * (2 * bq * 1024 + 2 * bk * 1024 + bq * bk) <= _VMEM_BUDGET_BYTES
    assert bq % 128 == 0 and bk % 128 == 0


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_flash_inner_matches_native(sp_mesh, causal):
    """Ulysses with the flash kernel as the inner attention (the TPU path)."""
    from accelerate_tpu.parallel.sequence_parallel import make_ulysses_attention

    q, k, v = _qkv(t=32, h=4)
    ref = native_attention(q, k, v, causal=causal)
    inner = lambda q, k, v, causal: flash_attention(q, k, v, causal=causal, block_q=8, block_k=8, interpret=True)
    attn = make_ulysses_attention(sp_mesh, inner_attn=inner)
    spec = NamedSharding(sp_mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = attn(qs, ks, vs, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


@pytest.mark.slow
def test_model_level_ulysses_matches_native():
    """attn_implementation='ulysses' (the config-name entry added for sp×tp
    composition) produces native-attention logits under an active sp mesh —
    params are impl-independent, so one init serves both."""
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    Accelerator(parallelism_config=ParallelismConfig(sp_size=4, dp_shard_size=2))
    rng_np = np.random.default_rng(0)
    tokens = jnp.asarray(rng_np.integers(0, 256, (2, 32)), jnp.int32)
    base = LlamaConfig.tiny(num_key_value_heads=4, dtype=jnp.float32)
    native_model = LlamaForCausalLM(base)
    params = native_model.init(jax.random.key(0), tokens[:, :8])
    ref = np.asarray(native_model.apply(params, tokens))
    uly = LlamaForCausalLM(
        LlamaConfig.tiny(attn_implementation="ulysses", num_key_value_heads=4,
                         dtype=jnp.float32)
    )
    out = np.asarray(uly.apply(params, tokens))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-5)


@pytest.mark.slow
def test_cp_composes_with_scanned_offload_ladder():
    """The multi-chip long-context claim (docs/long_context.md: ">=131k via
    cp=2 by the same per-shard ladder") requires ring CP to compose with the
    single-chip ladder itself: scan_layers + remat_policy="offload" (+ the
    hybrid boundary split).  Pin that the composed stack trains — loss
    decreases over steps — through the full Accelerator path on the CPU
    mesh (offload storage degrades to device memory there; the scan/remat/
    boundary-naming structure is identical)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn
    from accelerate_tpu.models.llama import stack_layer_params
    from accelerate_tpu.state import AcceleratorState, GradientState
    import optax

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(cp_size=2, dp_shard_size=4),
        mixed_precision="bf16",
    )
    cfg = LlamaConfig.tiny(
        attn_implementation="ring", remat=True, remat_policy="offload",
        scan_layers=True, boundary_offload_fraction=0.5, dtype=jnp.float32,
    )
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    seq = 32  # divisible by 2*cp (zigzag chunk pairs)
    tokens = rng.integers(0, cfg.vocab_size, (4, seq)).astype(np.int32)
    shift_labels = np.roll(tokens, -1, axis=1)
    shift_labels[:, -1] = -100
    unrolled = LlamaForCausalLM(
        LlamaConfig.tiny(attn_implementation="ring", dtype=jnp.float32))
    params = stack_layer_params(unrolled.init(jax.random.key(0), jnp.asarray(tokens[:, :8])))
    state = acc.create_train_state(params, optax.adamw(1e-3), apply_fn=model.apply)
    step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
    losses = []
    for _ in range(4):
        with acc.maybe_context_parallel(
            buffers=[tokens, shift_labels], buffer_seq_dims=[1, 1]
        ) as (ids, labels):
            state, metrics = step(state, {"input_ids": ids, "shift_labels": labels})
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_sp_composes_with_scanned_offload_ladder():
    """Ulysses SP variant of the composition pin above: sequence-sharded
    inputs through a scan_layers + offload-remat model (docs/long_context.md
    names `sp=2` as the other route past the single-chip ceiling)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn
    from accelerate_tpu.models.llama import stack_layer_params
    from accelerate_tpu.state import AcceleratorState, GradientState
    import optax

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(sp_size=2, dp_shard_size=4),
        mixed_precision="bf16",
    )
    cfg = LlamaConfig.tiny(
        attn_implementation="ulysses", remat=True, remat_policy="offload",
        scan_layers=True, boundary_offload_fraction=0.5, dtype=jnp.float32,
    )
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    unrolled = LlamaForCausalLM(
        LlamaConfig.tiny(attn_implementation="ulysses", dtype=jnp.float32))
    params = stack_layer_params(unrolled.init(jax.random.key(0), jnp.asarray(tokens[:, :8])))
    state = acc.create_train_state(params, optax.adamw(1e-3), apply_fn=model.apply)
    step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
    spec = acc._default_batch_spec()(tokens)
    batch = {
        "input_ids": jax.device_put(jnp.asarray(tokens), NamedSharding(acc.mesh, spec)),
        "labels": jax.device_put(jnp.asarray(tokens), NamedSharding(acc.mesh, spec)),
    }
    losses = []
    for _ in range(4):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses
