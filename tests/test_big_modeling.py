"""Big-model loading tests (reference tests/test_big_modeling.py +
test_modeling_utils.py coverage: abstract init, size accounting, placement
planner, checkpoint streaming into shards, offload store roundtrip)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.big_modeling import (
    OffloadStore,
    abstract_init,
    compute_module_sizes,
    dispatch_model,
    infer_auto_placement,
    init_empty_weights,
    load_checkpoint_and_dispatch,
    load_checkpoint_in_model,
    offload_state_dict,
    offloaded_apply,
)
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM


def test_abstract_init_zero_memory():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    abstract = abstract_init(model, jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    leaves = jax.tree_util.tree_leaves(abstract)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    assert len(leaves) > 10


def test_init_empty_weights_context():
    with init_empty_weights():
        pass  # API-parity no-op


def test_compute_module_sizes():
    params = {"a": {"w": jnp.ones((4, 4), jnp.float32)}, "b": jnp.ones((2,), jnp.float32)}
    sizes = compute_module_sizes(params)
    assert sizes["a"] == 64
    assert sizes["b"] == 8
    assert sizes[""] == 72


def test_infer_auto_placement_overflow_to_cpu_disk():
    params = {
        "big": jax.ShapeDtypeStruct((1024,), jnp.float32),     # 4096 B
        "medium": jax.ShapeDtypeStruct((256,), jnp.float32),   # 1024 B
        "small": jax.ShapeDtypeStruct((64,), jnp.float32),     # 256 B
    }
    placement = infer_auto_placement(params, max_memory={0: 4200, "cpu": 1100})
    assert placement["big"] == 0
    assert placement["medium"] == "cpu"
    assert placement["small"] == "disk"


def test_infer_auto_placement_descends_below_root():
    """A flax-style tree has a single 'params' root bigger than any budget;
    the planner must split it across tiers instead of offloading wholesale."""
    params = {"params": {
        "layer0": {"w": jax.ShapeDtypeStruct((256,), jnp.float32)},   # 1024 B
        "layer1": {"w": jax.ShapeDtypeStruct((256,), jnp.float32)},   # 1024 B
        "layer2": {"w": jax.ShapeDtypeStruct((256,), jnp.float32)},   # 1024 B
    }}
    placement = infer_auto_placement(params, max_memory={0: 1100, "cpu": 1100})
    assert placement == {
        "params.layer0": 0, "params.layer1": "cpu", "params.layer2": "disk",
    }


def test_infer_auto_placement_no_split_paths():
    params = {"params": {
        "block": {
            "a": jax.ShapeDtypeStruct((256,), jnp.float32),
            "b": jax.ShapeDtypeStruct((256,), jnp.float32),
        },
    }}
    placement = infer_auto_placement(
        params, max_memory={0: 1100, "cpu": 4096}, no_split_paths=["params.block"]
    )
    # block (2048 B) may not be split: both halves land on cpu together
    assert placement == {"params.block": "cpu"}


def test_infer_auto_placement_raises_when_full():
    params = {"big": jax.ShapeDtypeStruct((1024,), jnp.float32)}
    with pytest.raises(ValueError, match="Cannot place"):
        infer_auto_placement(params, max_memory={0: 10, "cpu": 10}, offload_to_disk=False)


def test_offload_store_roundtrip(tmp_path):
    store = offload_state_dict(str(tmp_path), {"layer/w": np.arange(12.0).reshape(3, 4)})
    assert "layer/w" in store
    loaded = store.load("layer/w")
    assert isinstance(loaded, np.memmap)
    np.testing.assert_allclose(np.asarray(loaded), np.arange(12.0).reshape(3, 4))
    # fresh store instance reads the same index
    store2 = OffloadStore(str(tmp_path))
    np.testing.assert_allclose(np.asarray(store2.load("layer/w")), np.arange(12.0).reshape(3, 4))


def _save_tiny_checkpoint(tmp_path, model, cfg):
    from accelerate_tpu.checkpointing import save_model

    acc = Accelerator()
    params = model.init(jax.random.key(1), jnp.ones((1, 8), jnp.int32))
    save_model(acc, params, str(tmp_path / "ckpt"))
    return params


def test_load_checkpoint_in_model_sharded(tmp_path):
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    orig = _save_tiny_checkpoint(tmp_path, model, cfg)

    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state(reset_partial_state=True)
    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    abstract = abstract_init(model, jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    plan = acc._params_plan(abstract)
    params, store = load_checkpoint_in_model(abstract, str(tmp_path / "ckpt"), sharding_plan=plan)
    assert store is None
    # loaded values equal originals, now sharded over the mesh
    embed = params["params"]["embed_tokens"]["embedding"]
    assert isinstance(embed, jax.Array)
    assert len(embed.sharding.device_set) == 8
    np.testing.assert_allclose(
        np.asarray(embed), np.asarray(orig["params"]["embed_tokens"]["embedding"]), rtol=1e-6
    )
    # model runs with streamed params
    logits = model.apply(params, jnp.ones((2, 8), jnp.int32))
    assert logits.shape == (2, 8, cfg.vocab_size)


def test_load_checkpoint_shape_mismatch_raises(tmp_path):
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    _save_tiny_checkpoint(tmp_path, model, cfg)
    cfg2 = LlamaConfig.tiny(hidden_size=32)
    model2 = LlamaForCausalLM(cfg2)
    abstract = abstract_init(model2, jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="shape mismatch"):
        load_checkpoint_in_model(abstract, str(tmp_path / "ckpt"))


def test_load_checkpoint_and_dispatch(tmp_path):
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    orig = _save_tiny_checkpoint(tmp_path, model, cfg)
    params, store = load_checkpoint_and_dispatch(
        model, str(tmp_path / "ckpt"), sample_args=(jnp.ones((1, 8), jnp.int32),)
    )
    logits = model.apply(params, jnp.ones((1, 8), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_load_checkpoint_dotted_placement_and_int_target(tmp_path):
    """Placement keys use the dotted compute_module_sizes convention and may
    target a device index; both must be honored during streaming."""
    abstract = {"params": {
        "inner": {"w": jax.ShapeDtypeStruct((4,), jnp.float32)},
        "x": jax.ShapeDtypeStruct((4,), jnp.float32),
    }}
    np.savez(tmp_path / "ckpt.npz", **{
        "params.inner.w": np.arange(4, dtype=np.float32),
        "params.x": np.ones(4, dtype=np.float32),
    })
    params, _ = load_checkpoint_in_model(
        abstract, tmp_path / "ckpt.npz",
        offload_placement={"params.inner": "cpu", "params.x": 1},
    )
    assert isinstance(params["params"]["inner"]["w"], np.ndarray)
    assert not isinstance(params["params"]["inner"]["w"], jax.Array)
    assert params["params"]["x"].devices() == {jax.local_devices()[1]}


def test_offload_store_bulk_flush(tmp_path):
    store = OffloadStore(tmp_path, autoflush=False)
    store.save("a", np.ones(2))
    assert not store.index_file.exists()
    store.flush()
    assert json.loads(store.index_file.read_text())["a"]["shape"] == [2]
    # reopened store sees the flushed index
    assert "a" in OffloadStore(tmp_path)


@pytest.mark.slow
def test_offloaded_apply(tmp_path):
    params = {"w": np.arange(8.0).reshape(2, 4)}  # host numpy = "offloaded"
    apply_fn = lambda p, x: x @ p["w"]
    wrapped = offloaded_apply(apply_fn)
    out = wrapped(params, jnp.ones((3, 2)))
    np.testing.assert_allclose(np.asarray(out), np.ones((3, 2)) @ np.arange(8.0).reshape(2, 4))


@pytest.mark.slow
def test_dispatch_model_cpu_and_disk(tmp_path):
    params = {"a": jnp.ones((4,)), "b": jnp.ones((4,))}
    placed, store = dispatch_model(
        params, {"a": "cpu", "b": "disk"}, offload_folder=str(tmp_path)
    )
    assert isinstance(placed["a"], np.ndarray)
    assert isinstance(placed["b"], np.memmap)


@pytest.mark.slow
def test_init_params_leafwise_shapes_and_placement():
    """Leaf-streamed init returns a real param tree matching the abstract
    structure, placed on the plan (r2 regression: a decorator mixup once
    turned it into a context manager)."""
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.big_modeling import init_params_leafwise
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    acc = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8))
    model = LlamaForCausalLM(LlamaConfig.tiny())
    sample = jnp.ones((1, 8), jnp.int32)
    params = init_params_leafwise(model, acc, sample)
    abstract = jax.eval_shape(lambda: model.init(jax.random.key(0), sample))
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(abstract)
    jax.tree_util.tree_map(
        lambda got, want: (got.shape, got.dtype) == (want.shape, want.dtype) or (_ for _ in ()).throw(
            AssertionError(f"{got.shape}/{got.dtype} != {want.shape}/{want.dtype}")),
        params, abstract,
    )
    # norm scales are ones, matrices are random, and a forward pass runs
    assert float(params["params"]["norm"]["scale"][0]) == 1.0
    logits = model.apply(params, sample)
    assert logits.shape[:2] == (1, 8)


@pytest.mark.slow
def test_cpu_and_disk_offload_wrappers(tmp_path):
    """Reference-shaped cpu_offload/disk_offload: whole tree leaves the
    accelerator, the wrapped apply ships leaves just-in-time and computes
    the same outputs (reference big_modeling.py:175,:226)."""
    import accelerate_tpu as at

    params = {"dense": {"kernel": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
                        "bias": jnp.ones((4,))}}

    def apply_fn(p, x):
        return x @ p["dense"]["kernel"] + p["dense"]["bias"]

    x = jnp.ones((2, 3))
    want = np.asarray(apply_fn(params, x))

    placed, wrapped = at.cpu_offload(params, apply_fn)
    assert isinstance(placed["dense"]["kernel"], np.ndarray)
    np.testing.assert_allclose(np.asarray(wrapped(placed, x)), want)

    placed_d, wrapped_d = at.disk_offload(params, tmp_path / "off", apply_fn)
    assert isinstance(placed_d["dense"]["kernel"], np.memmap)
    np.testing.assert_allclose(np.asarray(wrapped_d(placed_d, x)), want)


def test_reference_parity_top_level_exports():
    """A reference user's imports resolve at the same top-level paths
    (reference src/accelerate/__init__.py surface; renames documented in
    docs/migrating.md)."""
    import accelerate_tpu as at

    for name in [
        "Accelerator", "PartialState", "AcceleratorState", "GradientState",
        "ParallelismConfig", "prepare_data_loader", "skip_first_batches",
        "init_empty_weights", "load_checkpoint_and_dispatch",
        "load_checkpoint_in_model", "dispatch_model", "cpu_offload",
        "disk_offload", "infer_auto_device_map", "offload_state_dict",
        "find_executable_batch_size", "notebook_launcher", "debug_launcher",
        "prepare_pipeline", "LocalSGD", "set_seed", "synchronize_rng_states",
    ]:
        assert hasattr(at, name), name
