"""Compiled-artifact auditing + deploy preflight (analysis/compiled_audit.py,
commands/preflight.py): GL301-GL306 over the planted/clean fixture twins,
the compile-event counter, the serving warmup/recompile guard, and the CLI
surface.  All CPU-safe: AOT compilation needs a backend but executes
nothing, and every compiled program here is tiny.

Budget discipline (tier-1 is compile-bound): the in-process tests compile
only toy 64x64 programs; the single tier-1 CLI smoke preflights the tiny
2-bucket serving ladder + the canonical train step — 5 programs, the
asserted ceiling.  Anything compiling more is marked slow.
"""

import importlib.util
import json
import os
import subprocess
import sys
import warnings
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from accelerate_tpu.analysis import (
    RULES,
    Report,
    Severity,
    apply_suppressions,
    audit_aot,
    audit_fn,
    audit_program_set,
    lint_paths,
    lint_source,
)
from accelerate_tpu.analysis.compiled_audit import (
    CompileCounter,
    aot_compile_program,
    audit_compiled,
    device_hbm_bytes,
    install_global_compile_counter,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "analysis_fixtures"


def _load_fixture(name):
    spec = importlib.util.spec_from_file_location(name, FIXTURES / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rules_of(report_or_findings):
    findings = getattr(report_or_findings, "unsuppressed", None)
    findings = findings() if findings else report_or_findings
    return {f.rule for f in findings}


def _cli(args, env_extra=None, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu", "preflight", *args],
        cwd=REPO, capture_output=True, text=True, timeout=timeout, env=env,
    )


# ---------------------------------------------------------------------------
# the compile-event counter
# ---------------------------------------------------------------------------


def test_compile_counter_counts_backend_compiles():
    with CompileCounter() as c:
        jax.jit(lambda x: x * 1.618034)(jnp.ones((7,)))
    first = c.count
    assert first >= 1
    # stopped: later compiles are not attributed to this counter
    jax.jit(lambda x: x * 2.618034)(jnp.ones((7,)))
    assert c.count == first


def test_global_counter_is_idempotent_and_monotonic():
    a = install_global_compile_counter()
    b = install_global_compile_counter()
    assert a is b
    before = a.count
    jax.jit(lambda x: x + 0.577216)(jnp.ones((3,)))
    assert a.count > before


# ---------------------------------------------------------------------------
# GL301/GL302: the compiled audit over the fixture twins
# ---------------------------------------------------------------------------


def test_gl301_planted_donation_not_aliased():
    mod = _load_fixture("planted_preflight")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # jax's own donation warning
        rep, row = audit_aot(
            mod.donation_dropped_step,
            *mod.example_args()["donation_dropped_step"],
            donate_argnums=(0,), label="planted",
        )
    assert "GL301" in _rules_of(rep), rep.render()
    assert row["aliased_bytes"] == 0 and row["donated_bytes"] > 0


def test_gl301_clean_twin_aliases_fully():
    mod = _load_fixture("clean_preflight")
    rep, row = audit_aot(
        mod.donation_dropped_step,
        *mod.example_args()["donation_dropped_step"],
        donate_argnums=(0,), label="clean",
    )
    assert not rep.unsuppressed(), rep.render()
    assert row["aliased_bytes"] == row["donated_bytes"] > 0


def test_gl301_immune_to_persistent_cache_deserialization(tmp_path):
    """The sharp edge the auditor must absorb: an executable DESERIALIZED
    from the persistent compilation cache loses its donation alias table
    (alias_size_in_bytes reads 0).  Warm the disk cache, clear the
    in-memory caches, deserialize via a jit call — the audit must still
    compile fresh and report the alias honestly (no false GL301)."""
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        jax.config.update("jax_compilation_cache_dir", str(tmp_path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

        def f(s, b):
            return s * 0.7 + b, (s * b).sum()

        def args():  # fresh buffers each call: the jit calls DONATE s
            return jnp.ones((64, 64)), jnp.ones((64, 64))

        jax.jit(f, donate_argnums=(0,))(*args())  # writes the disk entry
        jax.clear_caches()
        jax.jit(f, donate_argnums=(0,))(*args())  # deserializes (alias lost)
        rep, row = audit_aot(f, *args(), donate_argnums=(0,), label="poisoned")
        assert "GL301" not in _rules_of(rep), rep.render()
        assert row["aliased_bytes"] == row["donated_bytes"] > 0
        assert row["compile_events"] >= 1  # a REAL compile, not a cache read
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def test_gl301_slack_tolerates_scalar_members():
    # a non-aliased donated SCALAR stays under the default 1 KiB slack —
    # the shape XLA reasonably declines (step counters etc.)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rep, _ = audit_aot(
            lambda c, x: x * 2.0, jnp.int32(3), jnp.ones((8,)),
            donate_argnums=(0,), label="scalar-donation",
        )
    assert "GL301" not in _rules_of(rep), rep.render()


@pytest.mark.parametrize("fixture,expect_over", [
    ("planted_preflight", True), ("clean_preflight", False),
])
def test_gl302_hbm_budget(fixture, expect_over):
    mod = _load_fixture(fixture)
    rep, row = audit_aot(
        mod.hbm_hog_step, *mod.example_args()["hbm_hog_step"],
        label="hog", hbm_budget_bytes=4096,
    )
    assert ("GL302" in _rules_of(rep)) is expect_over, rep.render()
    assert row["hbm"]["total"] > 0


def test_device_hbm_bytes_explicit_budget_wins():
    assert device_hbm_bytes(2.0) == 2 * 2**30
    # CPU backend reports no bytes_limit -> None (GL302 skipped, not guessed)
    assert device_hbm_bytes(None) in (None,) or device_hbm_bytes(None) > 0


# ---------------------------------------------------------------------------
# GL303: the program set vs the predicted bucket ladder
# ---------------------------------------------------------------------------


def test_gl303_planted_stray_width_vs_clean_ladder():
    for name, expect in (("planted_preflight", True), ("clean_preflight", False)):
        mod = _load_fixture(name)
        rows = []
        with CompileCounter() as counter:
            for width in mod.COMPILED_WIDTHS:
                prog = aot_compile_program(
                    mod.prefill_like, jax.ShapeDtypeStruct((width,), jnp.int32),
                    label=f"prefill[{width}]",
                )
                _, row = audit_compiled(prog.compiled, label=f"prefill[{width}]")
                rows.append(row)
        findings = audit_program_set(
            rows, len(mod.BUCKETS), measured_compile_events=counter.count
        )
        assert (any(f.rule == "GL303" for f in findings)) is expect, (name, findings)


def test_gl303_extra_backend_compiles_flagged():
    rows = [{"program": "decode"}, {"program": "release"}]
    findings = audit_program_set(rows, 2, measured_compile_events=5)
    assert _rules_of(findings) == {"GL303"}
    # cache hits (measured < programs) are fine
    assert audit_program_set(rows, 2, measured_compile_events=0) == []


# ---------------------------------------------------------------------------
# GL304: donated promotion drift (jaxpr engine)
# ---------------------------------------------------------------------------


def test_gl304_planted_promotion_drift_flagged():
    mod = _load_fixture("planted_preflight")
    rep = audit_fn(
        mod.promotion_drift_step, *mod.example_args()["promotion_drift_step"],
        donate_argnums=(0,),
    )
    assert "GL304" in _rules_of(rep), rep.render()


def test_gl304_clean_twin_quiet():
    mod = _load_fixture("clean_preflight")
    rep = audit_fn(
        mod.promotion_drift_step, *mod.example_args()["promotion_drift_step"],
        donate_argnums=(0,),
    )
    assert not rep.unsuppressed(), rep.render()


def test_gl304_int_to_float_drift_variant():
    # a python FLOAT mixed into an int state: int32 -> f32 drift, same shape
    def f(state):
        return state + 0.5, state.sum()

    rep = audit_fn(
        f, jax.ShapeDtypeStruct((4, 4), jnp.int32), donate_argnums=(0,)
    )
    assert "GL304" in _rules_of(rep), rep.render()


# ---------------------------------------------------------------------------
# GL305/GL306: the AST recompile-cause rules
# ---------------------------------------------------------------------------


def test_gl305_fixture_twins():
    planted = lint_paths([FIXTURES / "planted_preflight.py"], excludes=())
    assert {"GL305", "GL306"} <= _rules_of(planted), planted.render()
    clean = lint_paths([FIXTURES / "clean_preflight.py"], excludes=())
    assert not clean.unsuppressed(), clean.render()


def test_gl305_static_args_are_exempt():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def f(spec, x):\n"
        "    return jnp.zeros(spec.shape[0]) + x\n"
        "@partial(jax.jit, static_argnames=('spec',))\n"
        "def g(x, spec):\n"
        "    return jnp.zeros(spec.shape[0]) + x\n"
    )
    assert lint_source(src, "m.py") == []


def test_gl305_jit_binding_statics_respected():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def f(spec, x):\n"
        "    return jnp.zeros(spec.shape[0]) + x\n"
        "jitted = jax.jit(f, static_argnums=(0,))\n"
    )
    assert lint_source(src, "m.py") == []
    bad = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def f(spec, x):\n"
        "    return jnp.zeros(spec.shape[0]) + x\n"
        "jitted = jax.jit(f)\n"
    )
    assert _rules_of(lint_source(bad, "m.py")) == {"GL305"}


def test_gl305_local_binding_is_the_documented_miss():
    # the width bound to a local first is not flagged (documented miss:
    # the serving engine's bucket-pinned programs read widths this way)
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(ids):\n"
        "    n = ids.shape[0]\n"
        "    return jnp.arange(n)\n"
    )
    assert lint_source(src, "m.py") == []


def test_gl306_loop_variants():
    src = (
        "import jax\n"
        "def a(xs):\n"
        "    for x in xs:\n"
        "        y = jax.jit(lambda v: v)(x)\n"
        "    return y\n"
        "def b(xs):\n"
        "    i = 0\n"
        "    while i < len(xs):\n"
        "        f = jax.jit(lambda v: v)\n"
        "        i += 1\n"
        "    return f\n"
    )
    findings = [f for f in lint_source(src, "m.py") if f.rule == "GL306"]
    assert len(findings) == 2
    # hoisted wrapper: quiet
    good = (
        "import jax\n"
        "f = jax.jit(lambda v: v)\n"
        "def a(xs):\n"
        "    for x in xs:\n"
        "        y = f(x)\n"
        "    return y\n"
    )
    assert lint_source(good, "m.py") == []


def test_new_rules_are_in_the_catalog():
    for rule_id in ("GL107", "GL301", "GL302", "GL303", "GL304", "GL305", "GL306",
                    "GL401", "GL402", "GL403", "GL404"):
        assert rule_id in RULES
        assert RULES[rule_id].summary and RULES[rule_id].fix_hint
    assert RULES["GL107"].severity == Severity.INFO
    assert RULES["GL301"].severity == Severity.ERROR
    assert RULES["GL302"].severity == Severity.ERROR
    assert RULES["GL301"].engine == RULES["GL302"].engine == "compiled"
    assert RULES["GL401"].severity == RULES["GL403"].severity == Severity.ERROR
    assert RULES["GL402"].severity == RULES["GL404"].severity == Severity.WARNING
    assert all(RULES[r].engine == "distributed"
               for r in ("GL401", "GL402", "GL403", "GL404"))


# ---------------------------------------------------------------------------
# the preflight engine pieces (in-process)
# ---------------------------------------------------------------------------


def test_preflight_serve_compiles_exactly_the_ladder():
    from accelerate_tpu.commands.preflight import preflight_serve
    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils.dataclasses import PreflightConfig, ServingPlugin

    plugin = ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40,
        prefill_chunk=32, prefill_buckets=(16, 32), decode_kernel="native",
    )
    model = LlamaForCausalLM(LlamaConfig.tiny())
    findings, rows = preflight_serve(
        PreflightConfig(), model=model, plugin=plugin,
        gen_config=GenerationConfig(),
    )
    report = Report(apply_suppressions(findings))
    assert not report.unsuppressed(), report.render()
    assert len(rows) == len(plugin.prefill_buckets) + 2
    labels = {r["program"] for r in rows}
    assert labels == {"decode", "release", "prefill[16]", "prefill[32]"}
    for row in rows:
        assert row["hbm"]["total"] > 0
        assert row["flops"] >= 0


@pytest.mark.slow
def test_preflight_serve_speculate_ladder_joins_the_program_set():
    """With speculation on, the verify bucket programs join the AOT-compiled
    set: one verify per speculate bucket rides next to the prefill ladder,
    GL301-303 audit the lot, and the GL303 prediction counts them (the
    heavier-ladder compiles live in the slow tier; the tier-1 preflight
    path keeps its <=5-compile budget with speculation off)."""
    from accelerate_tpu.commands.preflight import preflight_serve
    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.utils.dataclasses import PreflightConfig, ServingPlugin

    plugin = ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40,
        prefill_chunk=16, prefill_buckets=(16,), decode_kernel="native",
        speculate="ngram", speculate_k=4, speculate_buckets=(2, 4),
    )
    model = LlamaForCausalLM(LlamaConfig.tiny())
    findings, rows = preflight_serve(
        PreflightConfig(), model=model, plugin=plugin,
        gen_config=GenerationConfig(),
    )
    report = Report(apply_suppressions(findings))
    assert not report.unsuppressed(), report.render()
    assert len(rows) == len(plugin.prefill_buckets) + 2 + len(plugin.speculate_buckets)
    labels = {r["program"] for r in rows}
    assert labels == {"decode", "release", "prefill[16]", "verify[2]", "verify[4]"}
    for row in rows:
        assert row["hbm"]["total"] > 0


def test_preflight_program_loads_fixture_convention(tmp_path):
    from accelerate_tpu.commands.preflight import preflight_program
    from accelerate_tpu.utils.dataclasses import PreflightConfig

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        findings, rows = preflight_program(
            f"{FIXTURES / 'planted_preflight.py'}::donation_dropped_step::donate=0",
            PreflightConfig(),
        )
    assert "GL301" in {f.rule for f in findings}
    assert len(rows) == 1
    # a bad target is a loud GL002, the shared resolver contract
    findings, rows = preflight_program(
        f"{tmp_path / 'nope.py'}::fn", PreflightConfig()
    )
    assert {f.rule for f in findings} == {"GL002"} and rows == []


# ---------------------------------------------------------------------------
# serving warmup + runtime recompile guard
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_serving():
    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    return model, params, GenerationConfig(max_new_tokens=6)


def test_serving_replay_compile_twins_zero_after_warmup(tiny_serving):
    """The acceptance pin: a seeded replay reports compiles_measured ==
    compiles_predicted (== 0) after warmup — no mid-traffic recompile."""
    from accelerate_tpu.serving import ServingEngine, replay, synthesize_trace
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    model, params, gen = tiny_serving
    plugin = ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40,
        prefill_chunk=16, prefill_buckets=(8, 16), decode_kernel="native",
    )
    engine = ServingEngine(model, params, plugin, gen)
    assert engine.compile_events == 0  # nothing compiled at construction
    rep = replay(engine, synthesize_trace(3, 6, vocab_size=model.config.vocab_size))
    assert rep["compiles_predicted"] == 0
    assert rep["compiles_measured"] == rep["compiles_predicted"] == 0
    assert rep["programs_predicted"] == len(plugin.prefill_buckets) + 3
    assert rep["completed"] == rep["requests"] > 0
    # warmup is engine-side state: a second replay run skips it
    assert engine.warmed_up


def test_serving_warmup_is_a_scheduling_noop(tiny_serving):
    """Warmup compiles every program but records nothing: token results of
    a warmed engine are identical to a cold one's (the greedy-parity
    contract extends through warmup)."""
    from accelerate_tpu.serving import ServingEngine, synthesize_trace
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    model, params, gen = tiny_serving
    plugin = ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40,
        prefill_chunk=16, prefill_buckets=(8, 16), decode_kernel="native",
    )
    trace = synthesize_trace(5, 5, vocab_size=model.config.vocab_size)
    cold = ServingEngine(model, params, plugin, gen)
    cold_results = cold.run(list(trace))
    warm = ServingEngine(model, params, plugin, gen)
    warm.warmup()
    assert warm.steps == 0 and warm.idle()
    after_warmup = warm.compile_events
    warm_results = warm.run(list(trace))
    assert warm_results == cold_results
    # post-warmup the replay was compile-free (the fixed-shape contract)
    assert warm.compile_events == after_warmup


def test_engine_warmup_programs_match_the_static_plan(tiny_serving):
    """``ServingEngine.warmup_programs()`` is the GL404 audit's
    ``warmup_plan`` read off the live engine — one derivation for the
    runtime warmup body and the preflight gate, pinned here against the
    exact label set the tiny ladder warms."""
    from accelerate_tpu.analysis import warmup_plan
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    model, params, gen = tiny_serving
    plugin = ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40,
        prefill_chunk=16, prefill_buckets=(8, 16), decode_kernel="native",
    )
    engine = ServingEngine(model, params, plugin, gen)
    progs = engine.warmup_programs()
    assert progs == frozenset(
        {"decode", "sample_first", "prefill[8]", "prefill[16]", "release"}
    )
    assert progs == warmup_plan(plugin)


def test_serving_warmup_refuses_mid_traffic(tiny_serving):
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.serving.scheduler import Request
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    model, params, gen = tiny_serving
    plugin = ServingPlugin(
        num_slots=4, page_size=4, pages_per_slot=16, num_pages=40,
        prefill_chunk=16, prefill_buckets=(8, 16), decode_kernel="native",
    )
    engine = ServingEngine(model, params, plugin, gen)
    engine.add_request(Request(uid=0, prompt=(1, 2, 3), max_new_tokens=4))
    engine.sched.admit()
    with pytest.raises(RuntimeError, match="before any traffic"):
        engine.warmup()


# ---------------------------------------------------------------------------
# the CLI (tier-1: ONE smoke, <= 5 compiled programs; failure paths ride
# in-process through the same command function)
# ---------------------------------------------------------------------------

_TINY_SERVE_ENV = {
    "ACCELERATE_SERVE_SLOTS": "4",
    "ACCELERATE_SERVE_PAGE_SIZE": "4",
    "ACCELERATE_SERVE_PAGES_PER_SLOT": "16",
    "ACCELERATE_SERVE_PAGES": "40",
    "ACCELERATE_SERVE_PREFILL_CHUNK": "32",
    "ACCELERATE_SERVE_KERNEL": "native",
}


def test_preflight_cli_smoke_tier1():
    """The acceptance command: ``python -m accelerate_tpu preflight --serve
    --train --disaggregate`` on the tiny CPU config compiles exactly
    len(buckets)+2 serving programs (+1 train step — 5 total, the tier-1
    ceiling; the pair audit is trace-only and adds NO compiled programs),
    reports per-program HBM + flops, embeds the distributed pair summary,
    exits 0 with zero unsuppressed findings, and its ``--json`` payload
    round-trips losslessly through ``Finding.from_dict``."""
    out = _cli(["--serve", "--train", "--disaggregate", "--json", "--no-lint"],
               env_extra=_TINY_SERVE_ENV)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    payload = json.loads(out.stdout)
    assert payload["summary"]["ok"] is True
    assert payload["summary"]["error"] == payload["summary"]["warning"] == 0
    programs = payload["programs"]
    # tiny 2-bucket ladder (prefill_chunk=32 -> buckets (16, 32)): decode +
    # release + 2 prefills + the train step — the tier-1 <=5 budget guard.
    # --disaggregate rides along without growing the compiled set.
    assert len(programs) == 2 + 2 + 1 <= 5
    serve_labels = {p["program"] for p in programs if "train" not in p["program"]}
    assert serve_labels == {"decode", "release", "prefill[16]", "prefill[32]"}
    for p in programs:
        assert p["hbm"]["total"] > 0, p
        assert "flops" in p and "bytes_accessed" in p and "compile_s" in p
    dist = payload["distributed"]
    assert dist["schema_ok"] is True and dist["findings"] == 0
    assert set(dist["roles"]) == {"prefill", "decode"}
    for role in dist["roles"].values():
        assert role["page_bytes"] > 0
    # the machine-readable findings list reconstructs to an identical report
    from accelerate_tpu.analysis import Finding

    rebuilt = Report([Finding.from_dict(d) for d in payload["findings"]])
    assert rebuilt.summary() == payload["summary"]
    assert [f.to_dict() for f in rebuilt.findings] == payload["findings"]


def _run_inprocess_cli(argv):
    from accelerate_tpu.commands.preflight import (
        preflight_command, preflight_command_parser,
    )

    args = preflight_command_parser().parse_args(argv)
    with pytest.raises(SystemExit) as exc:
        preflight_command(args)
    return exc.value.code


def test_preflight_cli_hbm_budget_exit_nonzero(capsys):
    mod_path = FIXTURES / "planted_preflight.py"
    code = _run_inprocess_cli([
        "--no-lint", "--hbm-gb", "0.0000001",
        "--program", f"{mod_path}::hbm_hog_step",
    ])
    assert code == 1
    assert "GL302" in capsys.readouterr().out


def test_preflight_cli_planted_donation_exit_nonzero(capsys):
    mod_path = FIXTURES / "planted_preflight.py"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        code = _run_inprocess_cli([
            "--no-lint",
            "--program", f"{mod_path}::donation_dropped_step::donate=0",
        ])
    assert code == 1
    assert "GL301" in capsys.readouterr().out


def test_preflight_cli_disaggregate_pair_gate(monkeypatch, capsys):
    """The pair gate, in-process (``--disaggregate`` alone is trace-only —
    no train/serve compiles ride along): the in-tree matched pair exits 0;
    an ``ACCELERATE_SERVE_PREFILL_KV_DTYPE`` role override plants a wire
    schema mismatch and the same command exits 1 naming GL403."""
    for key, value in _TINY_SERVE_ENV.items():
        monkeypatch.setenv(key, value)
    code = _run_inprocess_cli(["--disaggregate", "--no-lint"])
    out = capsys.readouterr().out
    assert code == 0, out
    assert "schema_ok=True" in out

    monkeypatch.setenv("ACCELERATE_SERVE_PREFILL_KV_DTYPE", "int8")
    code = _run_inprocess_cli(["--disaggregate", "--no-lint"])
    out = capsys.readouterr().out
    assert code == 1, out
    assert "GL403" in out and "schema_ok=False" in out


def test_preflight_and_lint_share_loud_missing_target(tmp_path, capsys):
    """The factored resolver contract: the same typo'd path is a non-zero
    GL002 exit in BOTH CLIs — never a silently skipped target."""
    from accelerate_tpu.commands.lint import lint_command, lint_command_parser

    missing = str(tmp_path / "typo.py")
    code = _run_inprocess_cli(["--no-lint", "--program", f"{missing}::fn"])
    assert code == 1 and "GL002" in capsys.readouterr().out
    code2 = _run_inprocess_cli([missing, "--train"])
    assert code2 == 1 and "GL002" in capsys.readouterr().out

    args = lint_command_parser().parse_args(["--no-step-audit", missing])
    with pytest.raises(SystemExit) as exc:
        lint_command(args)
    assert exc.value.code == 1 and "GL002" in capsys.readouterr().out
