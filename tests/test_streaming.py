"""ops/streaming.py — the double-buffered host↔device streaming pipeline.

The accelerator's chunked host update and generate_streamed's layer
prefetcher are both built from these pieces; their end-to-end parity lives
in tests/test_offload.py and tests/test_generation.py.  Here the machinery
itself is pinned: chunk partitioning (a numerics contract — SR hash streams
key on group-relative leaf indices), congruent slice/merge round-trips,
prefetcher ordering/accounting, and the overlap arithmetic bench.py emits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.streaming import (
    HOST_BYTES_PER_PARAM,
    LayerPrefetcher,
    StreamStats,
    chunk_groups,
    merge_congruent,
    offload_transfer_accounting,
    predicted_overlap,
    slice_congruent,
    stage_put,
    tree_bytes,
)


def _params():
    return {
        "a": {"kernel": jnp.arange(12.0).reshape(3, 4), "bias": jnp.zeros((4,))},
        "b": {"kernel": jnp.ones((4, 2)), "bias": jnp.full((2,), 3.0)},
    }


def test_tree_bytes_concrete_and_abstract():
    p = _params()
    want = sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(p))
    assert tree_bytes(p) == want
    abstract = jax.eval_shape(lambda: p)
    assert tree_bytes(abstract) == want


def test_chunk_groups_partition_and_bounds():
    p = _params()
    leaves = jax.tree_util.tree_leaves(p)
    # one leaf per group at a tiny budget
    groups = chunk_groups(p, 1)
    assert groups == [[i] for i in range(len(leaves))]
    # everything in one group at a huge budget
    assert chunk_groups(p, 1 << 40) == [list(range(len(leaves)))]
    # arbitrary budget: a contiguous exact partition, each group under
    # budget unless it is a single oversized leaf
    budget = 40
    groups = chunk_groups(p, budget)
    assert sorted(i for g in groups for i in g) == list(range(len(leaves)))
    for g in groups:
        size = sum(int(np.prod(leaves[i].shape)) * 4 for i in g)
        assert size <= budget or len(g) == 1


def test_slice_merge_congruent_roundtrip_with_scalar_state():
    p = _params()
    treedef = jax.tree_util.tree_structure(p)
    # adam-shaped state: congruent moment tree + a shared scalar count
    state = {"mu": jax.tree_util.tree_map(lambda x: x * 2, p), "count": jnp.int32(7)}
    groups = chunk_groups(p, 1)
    outs = []
    for idxs in groups:
        sl = slice_congruent(state, treedef, idxs)
        assert isinstance(sl["mu"], tuple) and len(sl["mu"]) == len(idxs)
        assert sl["count"].shape == ()  # scalar passes whole
        outs.append(sl)
    merged = merge_congruent(state, outs, treedef, groups)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), merged, state
    )


def test_stage_put_identity_and_placement():
    p = _params()
    # None shardings pass through untouched
    none_sh = jax.tree_util.tree_map(lambda _: None, p)
    out = stage_put(p, none_sh)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b), out, p)
    # real shardings place without changing values (the bitwise contract the
    # accelerator's stage A/C lean on)
    sh = jax.tree_util.tree_map(
        lambda _: jax.sharding.SingleDeviceSharding(jax.devices()[0]), p
    )
    placed = stage_put(p, sh)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b), placed, p)
    assert all(
        leaf.sharding == jax.sharding.SingleDeviceSharding(jax.devices()[0])
        for leaf in jax.tree_util.tree_leaves(placed)
    )


class _CountingFetch:
    def __init__(self, n):
        self.layers = [{"w": jnp.full((4,), float(i))} for i in range(n)]
        self.calls: list[int] = []

    def __call__(self, i):
        self.calls.append(i)
        return self.layers[i]


def test_layer_prefetcher_values_and_single_fetch_per_layer():
    fetch = _CountingFetch(4)
    stats = StreamStats()
    pf = LayerPrefetcher(fetch, 4, stats=stats)
    for i in range(4):
        out = pf.get(i)
        np.testing.assert_array_equal(np.asarray(out["w"]), np.full((4,), float(i)))
    # one fetch per layer — layers 1..3 were issued as prefetches
    assert sorted(fetch.calls) == [0, 1, 2, 3]
    assert stats.fetches == 4 and stats.prefetch_hits == 3
    assert stats.h2d_bytes == 4 * 4 * 4  # 4 layers x 4 floats


def test_layer_prefetcher_dispatch_order():
    fetch = _CountingFetch(3)
    pf = LayerPrefetcher(fetch, 3)
    pf.get(0)
    # cold miss: the layer needed NOW is dispatched first (queueing the
    # lookahead ahead of it would delay time-to-first-token), then layer
    # 1's upload is in flight before get(0) returns (the double buffer)
    assert fetch.calls == [0, 1]
    pf.get(1)
    # hit: only the lookahead (layer 2) is newly dispatched
    assert fetch.calls == [0, 1, 2]


def test_layer_prefetcher_depth0_explicit_prefetch():
    """depth=0 disables the sequential lookahead; the caller drives the
    double buffer through prefetch() — the adapter hot-swap contract
    (serving/adapters.py), where "next" is a scheduler decision, not i+1."""
    fetch = _CountingFetch(4)
    stats = StreamStats()
    pf = LayerPrefetcher(fetch, 4, depth=0, stats=stats)
    pf.get(0)
    assert fetch.calls == [0]          # no i+1 lookahead at depth 0
    assert pf.prefetch(2)              # explicit, non-blocking dispatch
    assert not pf.prefetch(2)          # already in flight: no re-issue
    out = pf.get(2)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.full((4,), 2.0))
    assert fetch.calls == [0, 2]       # the get() consumed the staged slot
    assert stats.prefetch_hits == 1
    with pytest.raises(IndexError):
        pf.prefetch(9)
    with pytest.raises(ValueError):
        LayerPrefetcher(fetch, 4, depth=-1)


def test_layer_prefetcher_wrap_prefetches_layer0_for_next_pass():
    fetch = _CountingFetch(3)
    pf = LayerPrefetcher(fetch, 3, wrap=True)
    hits = 0
    for _ in range(2):  # two decode passes
        for i in range(3):
            before = len(fetch.calls)
            pf.get(i)
            # after the cold start, every get is a hit: the previous get
            # (incl. the wrap at the pass boundary) already issued it
            hits += fetch.calls[before:].count(i) == 0
    # 6 gets = 1 cold miss + 6 prefetch issues (one per get; the last is
    # layer 0 in flight for a third pass that never runs)
    assert len(fetch.calls) == 7
    assert hits == 5  # all but the cold first layer


def test_layer_prefetcher_depth_2():
    fetch = _CountingFetch(5)
    stats = StreamStats()
    pf = LayerPrefetcher(fetch, 5, depth=2, stats=stats)
    for i in range(5):
        pf.get(i)
    assert sorted(fetch.calls) == list(range(5))
    assert stats.prefetch_hits == 4  # all but layer 0


def test_layer_prefetcher_disabled_is_serial():
    fetch = _CountingFetch(3)
    stats = StreamStats()
    pf = LayerPrefetcher(fetch, 3, enabled=False, stats=stats)
    for i in range(3):
        pf.get(i)
    assert fetch.calls == [0, 1, 2]  # strict order, no lookahead
    assert stats.prefetch_hits == 0 and stats.fetches == 3


def test_layer_prefetcher_bounds():
    pf = LayerPrefetcher(_CountingFetch(2), 2)
    with pytest.raises(IndexError):
        pf.get(2)
    with pytest.raises(ValueError):
        LayerPrefetcher(_CountingFetch(1), 0)


def test_stream_stats_overlap_report():
    s = StreamStats(h2d_bytes=100, d2h_bytes=50, fetches=4, prefetch_hits=3,
                    fetch_wait_s=0.2, wall_s=2.0)
    rep = s.overlap_report(serial_transfer_s=1.0)
    assert rep["h2d_bytes"] == 100 and rep["d2h_bytes"] == 50
    assert rep["stall_frac"] == pytest.approx(0.1)
    assert rep["overlap_frac"] == pytest.approx(0.8)
    # no baseline -> no overlap_frac claim (honest accounting)
    assert "overlap_frac" not in s.overlap_report()


def test_predicted_overlap_regimes():
    assert predicted_overlap(1.0, 10.0) == 1.0   # host-bound: all hideable
    assert predicted_overlap(10.0, 1.0) == pytest.approx(0.1)
    assert predicted_overlap(0.0, 1.0) == 1.0


def test_offload_transfer_accounting_7b_shape():
    n = 7_000_000_000
    rep = offload_transfer_accounting(n, optimizer="lion-sr",
                                      grad_bytes_per_param=2)
    assert rep["d2h_bytes"] == 2 * n and rep["h2d_bytes"] == 2 * n
    assert rep["host_update_bytes"] == int(HOST_BYTES_PER_PARAM["lion-sr"] * n)
    # the 7B regime is host-DRAM-bound: the whole transfer hides
    assert rep["overlap_frac"] == 1.0 and rep["kind"] == "predicted"
    resident = offload_transfer_accounting(n, optimizer="lion-sr",
                                           offload_params=False)
    assert resident["h2d_bytes"] == 0
