"""PowerSGD gradient compression (reference DDPCommunicationHookType.POWER_SGD
analog): factor math, convergence parity on the 8-device mesh, wire-bytes
accounting, and config validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.parallel.powersgd import (
    compress_decompress,
    eligible,
    init_powersgd_state,
    wire_bytes_report,
)
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.utils.dataclasses import (
    FullyShardedDataParallelPlugin,
    GradSyncKwargs,
    ShardingStrategy,
)


def _mlp_init(key, d_in=8, d_h=32):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (d_in, d_h)) * 0.3,
        "b1": jnp.zeros((d_h,)),
        "w2": jax.random.normal(k2, (d_h, 1)) * 0.3,
    }


def _mlp_loss(params, batch):
    h = jax.nn.relu(batch["x"] @ params["w1"] + params["b1"])
    pred = (h @ params["w2"])[:, 0]
    return jnp.mean((pred - batch["y"]) ** 2)


def _make_batches(n_batches=8, bs=32, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(8,)).astype(np.float32)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(bs, 8)).astype(np.float32)
        y = x @ w_true + 0.01 * rng.normal(size=(bs,)).astype(np.float32)
        out.append({"x": x, "y": y})
    return out


def _train(acc, n_epochs=30, lr=0.05):
    import optax

    state = acc.create_train_state(_mlp_init(jax.random.key(0)), acc.prepare(optax.sgd(lr)))
    step = acc.prepare_train_step(_mlp_loss)
    batches = _make_batches()
    losses = []
    for _ in range(n_epochs):
        for b in batches:
            state, metrics = step(state, b)
            losses.append(float(metrics["loss"]))
    return state, losses


def _fresh():
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def test_powersgd_converges_close_to_dense():
    _fresh()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=ShardingStrategy.NO_SHARD
        ),
        kwargs_handlers=[GradSyncKwargs(compression="powersgd", rank=2)],
    )
    state, losses = _train(acc)
    assert losses[-1] < 0.05, f"powersgd run failed to converge: {losses[-10:]}"

    _fresh()
    dense_acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=ShardingStrategy.NO_SHARD
        ),
    )
    dense_state, dense_losses = _train(dense_acc)
    # error feedback makes low-rank compression track the dense run's
    # convergence (not bit-exact — the approximation is the point)
    assert losses[-1] < max(dense_losses[-1] * 5, 0.05)


def test_powersgd_state_updates_and_errors_are_per_rank():
    _fresh()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=ShardingStrategy.NO_SHARD
        ),
        kwargs_handlers=[GradSyncKwargs(compression="powersgd", rank=2)],
    )
    import optax

    state = acc.create_train_state(_mlp_init(jax.random.key(0)), acc.prepare(optax.sgd(0.05)))
    qs, errs = state.comm_state
    assert qs["w1"].shape == (32, 2) and qs["b1"] is None
    assert errs["w1"].shape == (8, 8, 32)  # [dp, *leaf]
    q_before = np.asarray(qs["w1"]).copy()  # the step donates its input state
    step = acc.prepare_train_step(_mlp_loss)
    b = _make_batches(1)[0]
    state, _ = step(state, b)
    qs2, errs2 = state.comm_state
    # warm-start factors moved and residuals became nonzero
    assert float(jnp.abs(qs2["w1"] - q_before).max()) > 0
    assert float(jnp.abs(errs2["w1"]).max()) > 0
    # different ranks hold different residuals (their local grads differ)
    e = np.asarray(errs2["w1"])
    assert not np.allclose(e[0], e[1])


def test_powersgd_exact_when_rank_spans_gradient():
    """A rank-1 outer-product gradient is reproduced exactly (up to float)
    by rank>=1 compression with zero error."""
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp_shard",))
    g_global = jnp.outer(jnp.arange(1.0, 9.0), jnp.ones(16))  # rank 1, [8, 16]
    qs, errs = init_powersgd_state({"w": g_global}, rank=2, dp_size=4)

    def local(qs, errs):
        grads = {"w": g_global}  # identical on every rank
        e_local = jax.tree_util.tree_map(lambda e: e[0], errs)
        g_hat, new_qs, new_errs = compress_decompress(
            grads, qs, e_local, ("dp_shard",), 2
        )
        return g_hat, jax.tree_util.tree_map(lambda e: e[None], new_errs)

    from shard_map_compat import NO_CHECK, shard_map

    P = jax.sharding.PartitionSpec
    g_hat, new_errs = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(("dp_shard",))), out_specs=(P(), P(("dp_shard",))),
        **NO_CHECK,
    ))(qs, errs)
    np.testing.assert_allclose(np.asarray(g_hat["w"]), np.asarray(g_global), rtol=1e-4, atol=1e-4)
    assert float(jnp.abs(new_errs["w"]).max()) < 1e-4


def test_powersgd_allows_declared_full_shard_with_replicated_params():
    """FULL_SHARD with a trivial dp_shard axis shards nothing — params are
    replicated (the DDP shape powersgd targets), so the guard must accept."""
    _fresh()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_replicate_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=ShardingStrategy.FULL_SHARD
        ),
        kwargs_handlers=[GradSyncKwargs(compression="powersgd", rank=2)],
    )
    import optax

    state = acc.create_train_state(_mlp_init(jax.random.key(0)), acc.prepare(optax.sgd(0.05)))
    step = acc.prepare_train_step(_mlp_loss)
    state, metrics = step(state, _make_batches(1)[0])
    assert np.isfinite(float(metrics["loss"]))


def test_wire_bytes_report():
    params = {"w": jnp.zeros((1024, 1024)), "b": jnp.zeros((1024,))}
    rep = wire_bytes_report(params, rank=4)
    assert rep["eligible_leaves"] == 1 and rep["dense_leaves"] == 1
    dense_w = 1024 * 1024 * 4
    assert rep["dense_bytes_per_step"] == dense_w + 1024 * 4
    # P psum (n*r) + Q psum (m*r) floats for the matrix, dense for the bias
    assert rep["compressed_bytes_per_step"] == 4 * (1024 + 1024) * 4 + 1024 * 4
    assert rep["ratio"] < 0.02


def test_eligibility():
    assert eligible(jnp.zeros((64, 64)), 4)
    assert not eligible(jnp.zeros((64,)), 4)        # 1-D
    assert not eligible(jnp.zeros((4, 4)), 4)       # factors beat nothing
    assert not eligible(jnp.zeros((8, 8), jnp.int32), 2)


def test_powersgd_rejects_bad_configs():
    _fresh()
    acc = Accelerator(
        gradient_accumulation_steps=2,
        kwargs_handlers=[GradSyncKwargs(compression="powersgd")],
    )
    with pytest.raises(ValueError, match="accum"):
        acc.prepare_train_step(_mlp_loss)
    _fresh()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=4, tp_size=2),
        kwargs_handlers=[GradSyncKwargs(compression="powersgd")],
    )
    with pytest.raises(ValueError, match="tp"):
        acc.prepare_train_step(_mlp_loss)
    _fresh()
    # dp_shard>1 with no plugin defaults to FULL_SHARD: params sharded over
    # dp would force a per-step param all-gather inside the shard_map,
    # inverting the compression's wire-bytes purpose (ADVICE r4)
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        kwargs_handlers=[GradSyncKwargs(compression="powersgd")],
    )
    with pytest.raises(ValueError, match="params-sharded"):
        acc.prepare_train_step(_mlp_loss)
    _fresh()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(
            sharding_strategy=ShardingStrategy.HYBRID_SHARD
        ),
        kwargs_handlers=[GradSyncKwargs(compression="powersgd")],
    )
    with pytest.raises(ValueError, match="params-sharded"):
        acc.prepare_train_step(_mlp_loss)
