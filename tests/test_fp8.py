"""fp8 end-to-end training tests (reference fp8 integration: ao.py /
transformer_engine.py / fp8_utils, wired via mixed_precision="fp8" —
examples/torch_native_parallelism/README.md claims ~25% throughput on
H100s; here the path is QuantizableDense -> fp8_current_scaled_dot under
the fp8_autocast trace-time region).

On the CPU mesh fp8 dtypes are emulated, so these tests pin semantics
(routing, gradients, loss parity with bf16), not speed; the measured v5e
delta is recorded in benchmarks/README.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn
from accelerate_tpu.models.layers import QuantizableDense
from accelerate_tpu.ops.precision import (
    Fp8Meta,
    fp8_autocast,
    fp8_current_scaled_dot,
    fp8_dot,
    fp8_enabled,
)
from accelerate_tpu.state import AcceleratorState, GradientState


def test_fp8_autocast_flag_nesting():
    assert not fp8_enabled()
    with fp8_autocast():
        assert fp8_enabled()
        with fp8_autocast(enabled=False):
            assert not fp8_enabled()
        assert fp8_enabled()
    assert not fp8_enabled()


def test_fp8_current_scaled_dot_accuracy_and_grads():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 64)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.bfloat16)

    def loss8(x, w):
        return jnp.mean(fp8_current_scaled_dot(x, w).astype(jnp.float32) ** 2)

    def loss16(x, w):
        return jnp.mean(jnp.dot(x, w).astype(jnp.float32) ** 2)

    l8, (gx8, gw8) = jax.value_and_grad(loss8, argnums=(0, 1))(x, w)
    l16, (gx16, gw16) = jax.value_and_grad(loss16, argnums=(0, 1))(x, w)
    assert abs(float(l8) - float(l16)) < 0.1 * float(l16)
    # straight-through bwd: grads close to the bf16 reference
    for a, b in ((gx8, gx16), (gw8, gw16)):
        num = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        den = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-6
        assert num / den < 0.15, num / den


@pytest.mark.slow
def test_fp8_dot_delayed_scaling_meta_updates():
    x = jnp.ones((4, 16), jnp.bfloat16) * 3.0
    w = jnp.ones((16, 8), jnp.bfloat16) * 0.5
    out, (xm, wm) = fp8_dot(x, w, Fp8Meta.init(), Fp8Meta.init())
    assert out.shape == (4, 8)
    assert float(xm.amax_history[0]) == pytest.approx(3.0)
    assert float(wm.amax_history[0]) == pytest.approx(0.5)
    assert float(xm.scale) > 1.0  # 448 / 3


def test_quantizable_dense_routes_fp8():
    m = QuantizableDense(features=32, use_bias=False, dtype=jnp.bfloat16)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 64)), jnp.bfloat16)
    params = m.init(jax.random.PRNGKey(0), x)
    ref = m.apply(params, x)
    with fp8_autocast():
        out = m.apply(params, x)
    # fp8 introduces quantization error — close but not identical
    diff = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32))))
    assert 0 < diff < 0.1 * (float(jnp.max(jnp.abs(ref.astype(jnp.float32)))) + 1e-6)


def _train_llama(mixed_precision, n_steps=8):
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        mixed_precision=mixed_precision,
    )
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    state = acc.create_train_state(params, optax.adamw(1e-3), apply_fn=model.apply)
    step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
    rng = np.random.default_rng(0)
    # one fixed batch: the convergence signal is memorization, which shows
    # in 8 steps where fresh random tokens would not
    toks = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(toks), "labels": jnp.asarray(toks)}
    losses = []
    for _ in range(n_steps):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses


@pytest.mark.slow
def test_fp8_hardware_gate_warns(caplog):
    """Requesting fp8 on hardware without fp8 matmul units warns loudly but
    honors the request (the CPU mesh has no fp8 units, so the gate fires
    here exactly as it does on TPU v5e)."""
    import logging

    from accelerate_tpu.ops.precision import fp8_hardware_supported

    assert not fp8_hardware_supported()  # CPU mesh
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    with caplog.at_level(logging.WARNING, logger="accelerate_tpu.state"):
        acc = Accelerator(mixed_precision="fp8")
    assert acc.mixed_precision == "fp8"  # explicit opt-out preserved
    assert any("no fp8 matmul units" in r.message for r in caplog.records)


@pytest.mark.slow
def test_fp8_hardware_gate_env_fallback(monkeypatch, caplog):
    """ACCELERATE_FP8_FALLBACK_BF16=true degrades to bf16 on unsupported
    hardware instead of training slower in fp8."""
    import logging

    monkeypatch.setenv("ACCELERATE_FP8_FALLBACK_BF16", "true")
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    with caplog.at_level(logging.WARNING, logger="accelerate_tpu.state"):
        acc = Accelerator(mixed_precision="fp8")
    assert acc.mixed_precision == "bf16"
    assert any("falling back to bf16" in r.message for r in caplog.records)
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def test_fp8_hardware_probe_kinds():
    """The capability probe keys on TPU generation (v6/Trillium+ have fp8
    MXU paths; v5e and earlier do not)."""
    from accelerate_tpu.ops.precision import _tpu_kind_has_fp8

    for kind, want in [("TPU v5 lite", False), ("TPU v4", False), ("TPU v5p", False),
                       ("TPU v6e", True), ("TPU v6 lite", True), ("TPU v7x", True)]:
        assert _tpu_kind_has_fp8(kind) is want, kind


# ---------------------------------------------------------------------------
# delayed scaling: fp8_state rides TrainState (ISSUE 17 tentpole leg 1)
# ---------------------------------------------------------------------------


def test_fp8_recipe_kwargs_env_and_validation(monkeypatch):
    from accelerate_tpu import FP8RecipeKwargs

    assert FP8RecipeKwargs().amax_history_len == 16  # TE default
    monkeypatch.setenv("ACCELERATE_FP8_AMAX_HISTORY_LEN", "32")
    monkeypatch.setenv("ACCELERATE_FP8_MARGIN", "2")
    r = FP8RecipeKwargs()
    assert r.amax_history_len == 32 and r.margin == 2
    assert FP8RecipeKwargs(amax_history_len=8).amax_history_len == 8  # explicit wins
    with pytest.raises(ValueError, match="amax_history_len"):
        FP8RecipeKwargs(amax_history_len=0)
    with pytest.raises(ValueError, match="margin"):
        FP8RecipeKwargs(margin=-1)
    with pytest.raises(ValueError, match="amax_compute_algo"):
        FP8RecipeKwargs(amax_compute_algo="mean")


def test_fp8_state_rides_train_state_and_checkpoints(tmp_path):
    """The delayed-scaling amax histories are TrainState citizens: sized by
    the FP8RecipeKwargs recipe, seeded with each kernel's current amax,
    rolled once per optimizer step (TE DelayedScaling contract), and they
    survive a save_state/load_state roundtrip."""
    import optax as _optax

    from accelerate_tpu import FP8RecipeKwargs
    from accelerate_tpu.utils.dataclasses import ProjectConfiguration

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        project_config=ProjectConfiguration(
            project_dir=str(tmp_path), automatic_checkpoint_naming=True
        ),
        mixed_precision="fp8",
        kwargs_handlers=[FP8RecipeKwargs(amax_history_len=4, margin=1)],
    )
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    state = acc.create_train_state(params, _optax.adamw(1e-3), apply_fn=model.apply)
    assert state.fp8_state is not None
    # snapshot with a REAL copy: the jitted step donates the state's
    # buffers, and on CPU np.asarray aliases them zero-copy — a donated
    # buffer would mutate the "snapshot" in place
    hists = [np.array(x, copy=True)
             for x in jax.tree_util.tree_leaves(state.fp8_state)
             if getattr(x, "ndim", 0) == 1]
    assert hists and all(h.shape == (4,) for h in hists)  # recipe honored
    # seeded with the kernel's current amax: step 0 quantizes with exactly
    # the current-scaling scale
    assert all(float(h[0]) > 0 for h in hists)

    step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(toks), "labels": jnp.asarray(toks)}
    state2, _ = step(state, batch)
    new_hists = [x for x in jax.tree_util.tree_leaves(state2.fp8_state)
                 if getattr(x, "ndim", 0) == 1]
    # one tick: the history rolled, slot 1 now carries the seed amax
    for old, new in zip(hists, new_hists):
        assert float(new[1]) == float(old[0])
        assert float(new[0]) > 0

    ckpt = acc.save_state(train_state=state2)
    template = acc.create_train_state(
        model.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32)),
        _optax.adamw(1e-3), apply_fn=model.apply,
    )
    restored = acc.load_state(ckpt, train_state=template)
    for a, b in zip(jax.tree_util.tree_leaves(restored.fp8_state),
                    jax.tree_util.tree_leaves(state2.fp8_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def test_fp8_ops_pass_gl110_scaling_audit():
    """Clean sweep: the repo's own fp8 matmuls carry their descale through
    the GL110 jaxpr audit (every fp8 dot's output feeds a mul/div by the
    combined scale before any other consumer)."""
    from accelerate_tpu.analysis.jaxpr_audit import audit_traced
    from accelerate_tpu.ops.fp8 import fp8_delayed_dot

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 64)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.bfloat16)
    meta = Fp8Meta.init(4).updated(jnp.float32(2.0), 448.0, 0)
    reports = {
        "current": audit_traced(
            jax.jit(lambda a, b: fp8_current_scaled_dot(a, b)).trace(x, w)),
        "delayed": audit_traced(
            jax.jit(lambda a, b: fp8_delayed_dot(a, b, meta)).trace(x, w)),
        "delayed_grad": audit_traced(jax.jit(jax.grad(
            lambda a, b: jnp.sum(fp8_delayed_dot(a, b, meta).astype(jnp.float32))
        )).trace(x, w)),
    }
    for name, rep in reports.items():
        hits = [f for f in rep.findings if f.rule == "GL110"]
        assert not hits, (name, [f.message for f in hits])


@pytest.mark.slow
def test_fp8_training_tracks_bf16():
    """mixed_precision="fp8" trains the tiny Llama to parity-class loss with
    bf16 (VERDICT r1 next #5 done-condition, on the CPU mesh)."""
    bf16 = _train_llama("bf16")
    fp8 = _train_llama("fp8")
    assert all(np.isfinite(fp8))
    # same trajectory within fp8 quantization noise
    for a, b in zip(fp8, bf16):
        assert abs(a - b) < 0.05 * abs(b) + 0.05, (fp8, bf16)
    # and it actually learns
    assert fp8[-1] < fp8[0]
