"""Speculative multi-token decode tests (ISSUE 13 / ROADMAP item 1): the
draft providers, the batched verify program's acceptance + page-rollback
arithmetic, and THE parity pin — greedy tokens through
``generate_paged(speculate=...)`` are BITWISE identical to ``generate()``,
including under eviction/recompute pressure and mixed LoRA tenant traffic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate, generate_paged
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.serving import (
    NgramDraft,
    Request,
    ServingEngine,
    Speculator,
    predicted_acceptance,
    replay,
    synthesize_trace,
)
from accelerate_tpu.serving.scheduler import ContinuousBatchingScheduler
from accelerate_tpu.utils.dataclasses import ServingPlugin


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _plugin(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 16)
    kw.setdefault("num_pages", 40)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("decode_kernel", "native")
    kw.setdefault("speculate", "ngram")
    kw.setdefault("speculate_k", 4)
    return ServingPlugin(**kw)


def _ref_tokens(model, params, prompt, n, **cfg_kw):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   GenerationConfig(max_new_tokens=n, **cfg_kw))
    return [int(x) for x in out[0]]


# ---------------------------------------------------------------------------
# draft providers (host-side, deterministic)
# ---------------------------------------------------------------------------


def test_ngram_draft_prompt_lookup():
    d = NgramDraft(max_ngram=3)
    # the trailing bigram (7, 8) occurred earlier, followed by 9, 10
    assert d.propose_one([1, 7, 8, 9, 10, 2, 7, 8], 3) == [9, 10, 2]
    # longest n-gram wins: trailing (5, 6) matches at two sites, the
    # 3-gram (4, 5, 6) disambiguates to the continuation after IT
    ctx = [4, 5, 6, 11, 9, 5, 6, 12, 4, 5, 6]
    assert d.propose_one(ctx, 2) == [11, 9]
    # no earlier occurrence of anything -> no drafts
    assert d.propose_one([1, 2, 3, 4], 3) == []
    # k clamps the continuation
    assert d.propose_one([7, 8, 9, 7, 8], 1) == [9]


def test_ngram_draft_batched_shapes_and_determinism():
    d = NgramDraft()
    ctxs = [[1, 2, 1, 2], [3, 4, 5], [9, 9, 9, 9, 9, 9, 9, 9]]
    drafts, lens = d.propose(ctxs, 4)
    assert drafts.shape == (3, 4) and lens.shape == (3,)
    assert lens[1] == 0                   # no repeat -> nothing proposed
    assert lens[2] == 4                   # unigram cycle fills the window
    assert list(drafts[2, :4]) == [9, 9, 9, 9]
    drafts2, lens2 = d.propose(ctxs, 4)
    np.testing.assert_array_equal(drafts, drafts2)
    np.testing.assert_array_equal(lens, lens2)


def test_speculator_clamps_depth_to_token_budget():
    sp = Speculator(NgramDraft(), 4, (4,))
    # a cycling context drafts the full k, but remaining-1 caps the depth:
    # a slot one token from max_new verifies at depth 0 (plain decode)
    drafts, spec = sp.draft([[5, 6, 5, 6, 5, 6, 5, 6]] * 2, [8, 1])
    assert spec[0] == 4 and spec[1] == 0  # min(draft_len, k=4, remaining-1)
    assert sp.bucket_for(0) == 4 and sp.bucket_for(4) == 4
    with pytest.raises(ValueError):
        Speculator(NgramDraft(), 4, (2,))  # ladder must cover k


def test_predicted_acceptance_arithmetic():
    """Hand-checkable replay: stream [9, 5, 6, 5] from prompt (5, 6, 5, 6).
    Pass 1 (e=1): context (5,6,5,6,9) has no 9-continuation beyond the
    unigram match at... -> drafts follow the last earlier occurrence; the
    acceptance count must equal the hand count."""
    d = NgramDraft()
    trace = [Request(uid=0, prompt=(5, 6, 5, 6), max_new_tokens=4)]
    results = {0: [9, 5, 6, 5]}
    pred = predicted_acceptance(trace, results, d, k=4)
    # walk: e=1 ctx=(5,6,5,6,9): no earlier 9 -> no drafts -> emit 1 (pass 1)
    # e=2 ctx=(..9,5): depth=min(4, 4-2-1)=1, trailing (6,5)? max bigram
    # (9,5) unseen; unigram 5 -> last earlier 5 at idx 2 -> cont (6,) ->
    # draft [6] matches stream[2]=6 -> m=1, emit 2 (pass 2)
    # e=4 = len(stream): done.  2 passes, 1 drafted, 1 accepted, 3 emitted.
    assert pred["verify_passes"] == 2
    assert pred["drafted"] == 1 and pred["accepted"] == 1
    assert pred["accept_rate"] == 1.0
    assert pred["tokens_per_step"] == 1.5


# ---------------------------------------------------------------------------
# THE parity pin: speculative greedy tokens == generate() tokens
# ---------------------------------------------------------------------------


def test_generate_paged_speculate_matches_generate(tiny_model):
    """Variable-length rows + EOS padding: speculation changes nothing
    about the emitted tokens (the acceptance contract extends)."""
    model, params = tiny_model
    batch = jnp.asarray([[5, 42, 7, 9], [11, 3, 0, 0]], jnp.int32)
    lens = jnp.asarray([4, 2])
    cfg = GenerationConfig(max_new_tokens=5, eos_token_id=2, pad_token_id=0)
    ref = generate(model, params, batch, cfg, prompt_lengths=lens)
    got = generate_paged(model, params, batch, cfg, prompt_lengths=lens,
                         speculate="ngram")
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_speculate_parity_under_eviction_pressure(tiny_model):
    """A pool too small for the offered load forces evictions mid-
    speculation: every request still emits exactly its solo-run tokens,
    rejected drafts rolled real pages back, and the host free-page mirror
    ends exactly in sync with the device allocator."""
    model, params = tiny_model
    rng = np.random.default_rng(1)
    prompts = [tuple(int(x) for x in rng.integers(1, 255, n)) for n in (9, 7, 8)]
    plugin = ServingPlugin(num_slots=3, page_size=2, pages_per_slot=10,
                           num_pages=12, prefill_chunk=8,
                           decode_kernel="native", speculate="ngram",
                           speculate_k=3)
    eng = ServingEngine(model, params, plugin,
                        GenerationConfig(max_new_tokens=8))
    for i, p in enumerate(prompts):
        eng.add_request(Request(uid=i, prompt=p, max_new_tokens=8))
    while not eng.idle():
        eng.step()
    assert eng.metrics["evictions"] > 0
    assert eng.metrics["speculative_rollbacks"] > 0
    assert eng.metrics["accepted_draft_tokens"] > 0
    assert eng.free_page_mirror_in_sync()
    for i, p in enumerate(prompts):
        assert eng.results[i] == _ref_tokens(model, params, p, 8), f"request {i}"


def test_draft_model_provider_proposes_fixed_shape(tiny_model):
    """The draft-model provider's windowed forward: one fixed-shape jitted
    program regardless of context length (shorter contexts right-pad,
    longer ones slide), proposals deterministic."""
    from accelerate_tpu.serving import DraftModelDraft

    model, params = tiny_model
    d = DraftModelDraft(model, params, window=8)
    ctxs = [[5, 42, 7], list(range(1, 20))]   # short + longer-than-window
    drafts, lens = d.propose(ctxs, 3)
    assert drafts.shape == (2, 3) and list(lens) == [3, 3]
    drafts2, _ = d.propose(ctxs, 3)
    np.testing.assert_array_equal(drafts, drafts2)


@pytest.mark.slow
def test_speculate_draft_model_parity_and_acceptance(tiny_model):
    """The draft-model e2e (slow tier per the test-budget note): tokens
    identical to generate(), and — since the draft IS the target — the
    drafts accept."""
    model, params = tiny_model
    rng = np.random.default_rng(5)
    batch = jnp.asarray(rng.integers(1, 255, (2, 5)), jnp.int32)
    g = GenerationConfig(max_new_tokens=8)
    ref = generate(model, params, batch, g)
    got = generate_paged(model, params, batch, g, speculate="draft",
                         draft_model=model, draft_params=params,
                         speculate_k=3)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.slow
def test_draft_model_strict_compiles_under_varying_occupancy(tiny_model):
    """Regression: the draft batch pads to the FULL slot width.  A shape
    tracking the live candidate count recompiled the draft forward the
    first time occupancy changed (staggered arrivals/retirements), tripping
    strict_compiles mid-traffic."""
    model, params = tiny_model
    plugin = ServingPlugin(num_slots=3, page_size=4, pages_per_slot=16,
                           num_pages=24, prefill_chunk=16,
                           decode_kernel="native", speculate="draft",
                           speculate_k=2)
    # staggered lengths + arrivals: occupancy sweeps 1 -> 2 -> 3 -> 2 -> 1
    trace = [
        Request(uid=0, prompt=(5, 42, 7), max_new_tokens=12, arrival_step=0),
        Request(uid=1, prompt=(9, 11), max_new_tokens=4, arrival_step=4),
        Request(uid=2, prompt=(3, 8, 2, 6), max_new_tokens=7, arrival_step=8),
    ]
    eng = ServingEngine(model, params, plugin,
                        GenerationConfig(max_new_tokens=12),
                        draft_model=model, draft_params=params)
    rep = replay(eng, trace)  # strict_compiles=True raises on a recompile
    assert rep["completed"] == 3 and rep["compiles_measured"] == 0
    for r in trace:
        assert rep["results"][r.uid] == _ref_tokens(
            model, params, r.prompt, r.max_new_tokens)
    # the draft-model predicted twin stays idle by design (no model-free
    # replay exists for a model's drafts) while the measured side records
    assert rep["accept_rate"] > 0 and rep["accept_rate_predicted"] == 0.0


def test_speculate_with_lora_tenant_mix(tiny_model, tmp_path):
    """Mixed-tenant traffic with hot-swap + page-pressure eviction, served
    speculatively: per-request tokens equal the dedicated single-request
    ``generate_paged`` pass with that adapter, zero post-warmup compiles
    (``strict_compiles`` raises otherwise), mirror in sync."""
    from accelerate_tpu.serving import AdapterStore
    from accelerate_tpu.utils.dataclasses import LoraPlugin

    model, params = tiny_model
    cfg = model.config
    lplug = LoraPlugin(rank=4, pool_slots=2, kernel="native")

    def store(d):
        s = AdapterStore(params, lplug, dtype=cfg.dtype, offload_dir=str(d))
        for t in (1, 2, 3):
            s.publish_random(t, jax.random.PRNGKey(1000 + t))
        return s

    splug = ServingPlugin(num_slots=4, page_size=2, pages_per_slot=10,
                          num_pages=14, prefill_chunk=8,
                          decode_kernel="native", speculate="ngram",
                          speculate_k=3)
    trace = synthesize_trace(3, 7, vocab_size=255, prompt_len_range=(3, 9),
                             new_tokens_range=(3, 6), adapters=3)
    eng = ServingEngine(model, params, splug,
                        GenerationConfig(max_new_tokens=32),
                        adapters=store(tmp_path / "a"))
    rep = replay(eng, trace)  # strict_compiles=True
    assert rep["completed"] == len(trace)
    assert rep["compiles_measured"] == 0
    assert eng.free_page_mirror_in_sync()
    ref_store = store(tmp_path / "b")
    for r in trace:
        out = generate_paged(model, params, jnp.asarray([r.prompt], jnp.int32),
                             GenerationConfig(max_new_tokens=r.max_new_tokens),
                             adapters=ref_store, adapter_ids=[r.adapter_id])
        ref = [int(x) for x in np.asarray(out[0])][: len(rep["results"][r.uid])]
        assert rep["results"][r.uid] == ref, f"request {r.uid} (tenant {r.adapter_id})"


# ---------------------------------------------------------------------------
# strict compiles, twins, metrics, determinism
# ---------------------------------------------------------------------------


def test_speculate_replay_strict_compiles_and_twins(tiny_model):
    """The seeded replay with speculation on: zero post-warmup compiles
    across the k-bucket ladder, tokens_per_step beats the plain-decode 1.0
    floor, and the accept-rate/tokens-per-step twins agree within their
    declared tolerance (registered in the central TwinRegistry)."""
    from accelerate_tpu.telemetry import twin_registry

    model, params = tiny_model
    trace = synthesize_trace(0, 16, vocab_size=255, mean_interarrival_steps=0.5,
                             prompt_len_range=(4, 24), new_tokens_range=(4, 24))
    eng = ServingEngine(model, params, _plugin(),
                        GenerationConfig(max_new_tokens=64))
    rep = replay(eng, trace)  # raises on any mid-traffic compile
    assert rep["compiles_measured"] == 0
    assert rep["speculate"] == "ngram" and rep["speculate_k"] == 4
    assert rep["tokens_per_step"] > 1.0
    assert rep["verify_steps"] > 0 and rep["accept_rate"] > 0
    # one verify program per bucket joins the predicted program set
    assert rep["programs_predicted"] == \
        len(eng.plugin.prefill_buckets) + 3 + len(eng.plugin.speculate_buckets)
    for name in ("speculate.accept_rate", "speculate.tokens_per_step"):
        twin = twin_registry().get(name)
        assert twin is not None and twin.status in ("ok", "warn"), \
            (name, twin and twin.row())
    assert eng.free_page_mirror_in_sync()


def test_speculate_scheduler_event_log_is_deterministic(tiny_model):
    """Same seed -> identical schedule including the per-pass accepted
    counts in the 'verify' events; a different seed schedules differently."""
    model, params = tiny_model
    gcfg = GenerationConfig(max_new_tokens=32)

    def run(seed):
        trace = synthesize_trace(seed, 8, vocab_size=255,
                                 prompt_len_range=(3, 10), new_tokens_range=(2, 6))
        eng = ServingEngine(model, params, _plugin(), gcfg)
        results = eng.run(trace)
        return eng.sched.events, results

    ev_a, res_a = run(7)
    ev_b, res_b = run(7)
    assert ev_a == ev_b and res_a == res_b
    assert any(ev[0] == "verify" for ev in ev_a)
    ev_c, _ = run(8)
    assert ev_c != ev_a


def test_speculate_verify_step_audits_donation_clean(tiny_model):
    """The verify program's allocate + multi-token append + page rollback
    pytree aliases the donated cache (no GL101/GL103/GL105)."""
    model, params = tiny_model
    eng = ServingEngine(model, params, _plugin(num_slots=2, num_pages=16),
                        GenerationConfig(max_new_tokens=4))
    rep = eng.audit_verify_step(default_memory_kind="device")
    assert not rep.unsuppressed(), rep.render()


# ---------------------------------------------------------------------------
# scheduler accounting (pure host arithmetic, no device programs)
# ---------------------------------------------------------------------------


def test_scheduler_speculative_page_accounting():
    sched = ContinuousBatchingScheduler(
        num_slots=2, num_pages=8, page_size=4, pages_per_slot=4,
        prefill_chunk=8, prefill_buckets=(8,), speculate_k=3,
    )
    # admission demands prompt + first-verify worst case, clamped by the
    # request's own budget — never more than submit guaranteed the pool has
    req = Request(uid=0, prompt=(1, 2, 3, 4, 5), max_new_tokens=8)
    # prompt: 2 pages; verify writes positions 5..8 -> page 2 -> 3 pages
    assert sched.admission_page_need(req) == 3
    short = Request(uid=1, prompt=(1, 2, 3), max_new_tokens=1)
    assert sched.admission_page_need(short) == 1  # depth 0: plain decode
    sched.submit(req)
    sched.admit()
    slot = next(iter(sched.slots))
    st = sched.slots[slot]
    st.prefilled = 5
    sched.free_pages = sched.num_pages - 2  # the 2 prompt pages
    st.tokens.append(42)  # first token sampled off the prefill logits
    # worst case for a depth-3 verify at kv=5: positions 5..8 cross into
    # page 2 -> exactly 1 fresh page
    assert sched.verify_page_need([slot], {slot: 3}) == {slot: 1}
    # device accepts m=2 -> kv 5 -> 8, pages for kv 8 = 2 (no new page...
    # positions 5,6,7 stay in page 1) -> consumed = pages_for(8)-pages_for(5) = 0
    sched.note_verify({slot: 2})
    assert st.kv_len == 8
    assert sched.free_pages == sched.num_pages - 2
    # next pass crosses the boundary: kv=8, depth 1 writes 8..9 -> 1 page
    assert sched.verify_page_need([slot], {slot: 1}) == {slot: 1}
    sched.note_verify({slot: 1})
    assert st.kv_len == 10 and sched.free_pages == sched.num_pages - 3
    # finish frees pages_for(kv_len)=3 — the kv_tokens discipline (NOT the
    # possibly-shorter host token list)
    sched.finish(slot)
    assert sched.free_pages == sched.num_pages


def test_scheduler_degrades_draft_depth_before_evicting():
    """Page pressure first COSTS DRAFT DEPTH, not live sequences: the
    worst-case speculative reservation is transient (rejected pages roll
    back), so the planner zeroes depths — youngest-admitted first — down
    to the plain-decode floor before the shared evict loop may run."""
    sched = ContinuousBatchingScheduler(
        num_slots=3, num_pages=6, page_size=2, pages_per_slot=4,
        prefill_chunk=4, prefill_buckets=(4,), speculate_k=2,
    )
    for uid in range(3):
        sched.submit(Request(uid=uid, prompt=(1, 2), max_new_tokens=6))
    admitted = sched.admit()
    assert len(admitted) == 3
    for s in admitted:
        st = sched.slots[s]
        st.prefilled = 2
        st.tokens.append(7)
    sched.free_pages = 2  # floor demand: 1 page/slot (kv=2 is a page start)
    spec = {s: 2 for s in admitted}
    survivors, evicted = sched.plan_speculative_evictions(list(admitted), spec)
    # worst case was 2 pages/slot = 6 > 2; floor is 3 > 2 -> depths zero
    # youngest-first, then ONE eviction covers the remaining floor deficit
    assert all(spec[s] == 0 for s in spec)
    assert any(ev[0] == "despeculate" for ev in sched.events)
    assert len(evicted) == 1 and len(survivors) == 2
    assert sum(sched.verify_page_need(survivors, spec).values()) <= sched.free_pages

    # with headroom for the floor but not the worst case: NO eviction at
    # all — depth degradation alone absorbs the pressure
    sched2 = ContinuousBatchingScheduler(
        num_slots=2, num_pages=8, page_size=2, pages_per_slot=4,
        prefill_chunk=4, prefill_buckets=(4,), speculate_k=2,
    )
    for uid in range(2):
        sched2.submit(Request(uid=uid, prompt=(1, 2), max_new_tokens=6))
    adm2 = sched2.admit()
    for s in adm2:
        sched2.slots[s].prefilled = 2
        sched2.slots[s].tokens.append(7)
    sched2.free_pages = 3  # fits one worst-case (2) + one floor (1)
    spec2 = {s: 2 for s in adm2}
    survivors2, evicted2 = sched2.plan_speculative_evictions(list(adm2), spec2)
    assert evicted2 == [] and set(survivors2) == set(adm2)
    assert sorted(spec2.values()) == [0, 2]  # only the youngest degraded


def test_generate_paged_speculate_false_overrides_armed_plugin(tiny_model):
    """speculate=False is an explicit opt-out: it must win over a plugin
    (or env) that armed speculation — the do_sample guard then never fires
    and sampling decodes through the plain path."""
    model, params = tiny_model
    batch = jnp.asarray([[5, 42, 7, 9]], jnp.int32)
    armed = ServingPlugin(num_slots=1, page_size=4, pages_per_slot=8,
                          num_pages=8, prefill_chunk=8, decode_kernel="native",
                          speculate="ngram", speculate_k=2)
    out = generate_paged(model, params, batch,
                         GenerationConfig(max_new_tokens=3, do_sample=True),
                         serving_plugin=armed, speculate=False)
    assert out.shape == (1, 3)


# ---------------------------------------------------------------------------
# plugin knobs + guards
# ---------------------------------------------------------------------------


def test_speculate_plugin_env_knobs(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SERVE_SPECULATE", "on")
    monkeypatch.setenv("ACCELERATE_SERVE_SPECULATE_K", "6")
    monkeypatch.setenv("ACCELERATE_SERVE_SPECULATE_DRAFT", "48")
    p = ServingPlugin()
    assert (p.speculate, p.speculate_k, p.speculate_draft_window) == ("ngram", 6, 48)
    assert p.speculate_buckets == (6,)
    # explicit arguments always win over env
    p2 = ServingPlugin(speculate="draft", speculate_k=2,
                       speculate_buckets=(2, 4))
    assert p2.speculate == "draft" and p2.speculate_buckets == (2, 4)
    monkeypatch.delenv("ACCELERATE_SERVE_SPECULATE")
    assert ServingPlugin().speculate == "off"
    # the generate_paged(speculate=True) boolean convention works on the
    # plugin too
    assert ServingPlugin(speculate=True).speculate == "ngram"
    assert ServingPlugin(speculate=False).speculate == "off"
    with pytest.raises(ValueError):
        ServingPlugin(speculate="mystery")
    with pytest.raises(ValueError):
        ServingPlugin(speculate="ngram", speculate_k=4, speculate_buckets=(2,))
    with pytest.raises(ValueError):
        ServingPlugin(speculate="ngram", speculate_k=0)


def test_speculate_guards(tiny_model):
    model, params = tiny_model
    # greedy only: sampling breaks the greedy-prefix acceptance pin
    with pytest.raises(ValueError, match="greedy"):
        ServingEngine(model, params, _plugin(),
                      GenerationConfig(max_new_tokens=4, do_sample=True))
    # draft mode needs the draft model
    with pytest.raises(ValueError, match="draft_model"):
        ServingEngine(model, params, _plugin(speculate="draft"),
                      GenerationConfig(max_new_tokens=4))
