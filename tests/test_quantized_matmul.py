"""Weight-only int8 matmul kernel tests (ops/quantized_matmul.py) — parity
with the dequantize+matmul reference, leading-dim handling, and the
fallback paths (nf4, non-lane-aligned blocks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.quantized_matmul import quantized_matmul
from accelerate_tpu.utils.quantization import (
    QuantizationConfig,
    dequantize,
    quantize,
)


@pytest.fixture(scope="module")
def wq():
    rng = np.random.default_rng(0)
    W = rng.normal(size=(256, 1024)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    return W, qt


def test_kernel_matches_dequant_matmul(wq):
    _, qt = wq
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 256)), jnp.bfloat16)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    out = quantized_matmul(x, qt, block_m=8, block_k=128, out_dtype=jnp.float32,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.2)


def test_kernel_leading_dims_and_dtype(wq):
    _, qt = wq
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 3, 256)), jnp.bfloat16)
    out = quantized_matmul(x, qt, block_m=8, block_k=128, interpret=True)
    assert out.shape == (2, 3, 1024)
    assert out.dtype == jnp.bfloat16


def test_kernel_accuracy_vs_fp32(wq):
    """End-to-end int8 error stays in the expected few-percent band."""
    W, qt = wq
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 256)), jnp.float32)
    exact = np.asarray(x) @ W
    out = np.asarray(quantized_matmul(x.astype(jnp.bfloat16), qt, block_m=8,
                                      block_k=128, out_dtype=jnp.float32,
                                      interpret=True))
    rel = np.abs(out - exact).max() / np.abs(exact).max()
    assert rel < 0.05, rel


def test_non_divisible_contraction_dim_clamps_k_tile():
    """H = 384 with block_k = 256: 256 does not divide 384, so the kernel
    must clamp to bk = 128 instead of accumulating padding on the last K
    step (ADVICE r1 high: all-NaN for h % block_k != 0)."""
    rng = np.random.default_rng(6)
    W = rng.normal(size=(384, 1024)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(4, 384)), jnp.bfloat16)
    out = quantized_matmul(x, qt, block_m=8, block_k=256, out_dtype=jnp.float32,
                           interpret=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.3)


def test_non_divisor_contraction_dim_masks_partial_tile():
    """H = 320 has no multiple-of-128 divisor <= block_k: the kernel takes
    a masked partial last K tile (select-zeroed rows) and stays exact."""
    rng = np.random.default_rng(7)
    W = rng.normal(size=(320, 1024)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(4, 320)), jnp.bfloat16)
    out = quantized_matmul(x, qt, block_k=256, out_dtype=jnp.float32, interpret=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.3)


def test_half_divisor_boundary_takes_masked_tile():
    """When the largest divisor is exactly half the requested block (the
    down_proj-style case), the masked full-size tile is chosen — gate is
    <=, not < (r2 review finding) — and stays exact."""
    rng = np.random.default_rng(9)
    W = rng.normal(size=(1280, 512)).astype(np.float32)  # divisor 256 = 512//2
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(4, 1280)), jnp.bfloat16)
    out = quantized_matmul(x, qt, block_k=512, out_dtype=jnp.float32, interpret=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    # bf16 accumulation-order noise at h=1280 reaches ~0.5 on outputs of
    # magnitude ~100; the masked tile is exact (NaN/garbage would be >>1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.6)


def test_unaligned_block_k_request_is_aligned_down():
    """A caller-supplied block_k that is not a multiple of 128 is aligned
    down instead of producing a Mosaic-illegal tile (r2 review finding)."""
    rng = np.random.default_rng(10)
    W = rng.normal(size=(1000, 256)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(4, 1000)), jnp.bfloat16)
    out = quantized_matmul(x, qt, block_k=200, out_dtype=jnp.float32, interpret=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.4)


def test_tiny_contraction_dim_falls_back():
    """H < 128 has no viable lane-aligned K tile at all -> dequant fallback."""
    rng = np.random.default_rng(8)
    W = rng.normal(size=(96, 256)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(4, 96)), jnp.bfloat16)
    out = quantized_matmul(x, qt, out_dtype=jnp.float32)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.3)


def test_k_tile_divisor_helper():
    """_k_tile finds the largest lane-aligned divisor (None when there is
    none).  Note the production path may override a small divisor with a
    masked full-size tile — e.g. Llama-7B's 11008 (divisor 256) runs masked
    bk=512; see test_half_divisor_boundary_takes_masked_tile."""
    from accelerate_tpu.ops.quantized_matmul import _k_tile

    assert _k_tile(11008, 512) == 256
    assert _k_tile(320, 256) is None
    assert _k_tile(256, 512) == 256


def test_wholef_decode_kernel_matches_dequant_matmul():
    """The whole-F contiguous-row decode kernel (auto-picked at m <= 8) is
    exact vs dequantize+matmul at a decode shape with a divisor K tile."""
    rng = np.random.default_rng(11)
    W = rng.normal(size=(2048, 1408)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(1, 2048)), jnp.bfloat16)
    out = quantized_matmul(x, qt, out_dtype=jnp.float32, interpret=True,
                           wholef=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    # kernel accumulates fp32 while the bf16 reference rounds per-output;
    # at h=2048 that honest gap reaches ~1 on outputs of magnitude ~90
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=0.02,
                               atol=1.0)


def test_wholef_masked_k_tail():
    """Whole-F path with H that has only a small lane divisor (Llama-7B
    down_proj-style): masked full-budget K tile, still exact."""
    rng = np.random.default_rng(12)
    W = rng.normal(size=(1408, 512)).astype(np.float32)  # 1408 = 128 * 11
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(4, 1408)), jnp.bfloat16)
    out = quantized_matmul(x, qt, out_dtype=jnp.float32, interpret=True,
                           wholef=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.6)


def test_wholef_partial_last_chunk():
    """F not a multiple of the dequant chunk: the static chunk loop's last
    slice is a partial (but whole-q-block) chunk."""
    rng = np.random.default_rng(13)
    W = rng.normal(size=(512, 640)).astype(np.float32)  # 640 = 5 q-blocks
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=128))
    x = jnp.asarray(rng.normal(size=(2, 512)), jnp.bfloat16)
    out = quantized_matmul(x, qt, out_dtype=jnp.float32, interpret=True,
                           wholef=True)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16)).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.4)


def test_wholef_tile_planner():
    from accelerate_tpu.ops.quantized_matmul import (
        _WHOLEF_TILE_BYTES, _wholef_tiles)

    bk, masked = _wholef_tiles(2048, 5632)
    assert not masked and 2048 % bk == 0 and bk * 5632 <= _WHOLEF_TILE_BYTES
    bk, masked = _wholef_tiles(11008, 4096)  # divisor only 256
    assert masked and bk == 1024
    assert _wholef_tiles(96, 1024) is None  # H below one lane width


def test_nf4_falls_back():
    rng = np.random.default_rng(4)
    W = rng.normal(size=(64, 256)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_4bit=True))
    x = jnp.asarray(rng.normal(size=(2, 64)), jnp.bfloat16)
    out = quantized_matmul(x, qt)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=0.3)


def test_small_block_falls_back():
    """block_size 64 (not lane-aligned) takes the dequant+matmul path and is
    still correct."""
    rng = np.random.default_rng(5)
    W = rng.normal(size=(64, 256)).astype(np.float32)
    qt = quantize(W, QuantizationConfig(load_in_8bit=True, block_size=64))
    x = jnp.asarray(rng.normal(size=(2, 64)), jnp.bfloat16)
    out = quantized_matmul(x, qt)
    ref = jnp.matmul(x, dequantize(qt, jnp.bfloat16))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=0.3)
