"""LocalSGD (SURVEY §2.4 P13) and utils/other analogs
(reference local_sgd.py / utils/other.py)."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import LocalSGD
from accelerate_tpu.local_sgd import ops as local_sgd_ops
from accelerate_tpu.utils.other import (
    aot_compile,
    check_os_kernel,
    compile_regions,
    extract_model_from_parallel,
    load,
    save,
)


class _FakeState:
    def __init__(self, params):
        self.params = params

    def replace(self, params):
        return _FakeState(params)


def test_local_sgd_single_process_noop():
    sgd = LocalSGD(local_sgd_steps=2)
    assert not sgd.enabled  # one process: degenerate no-op
    state = _FakeState({"w": jnp.ones((4,))})
    out = sgd.step(state)
    assert out is state


def test_local_sgd_cadence(monkeypatch):
    calls = []

    def fake_reduce(params, reduction="mean"):
        calls.append(reduction)
        return jax.tree.map(np.asarray, params)

    sgd = LocalSGD(local_sgd_steps=3)
    sgd.enabled = True  # pretend multi-process
    monkeypatch.setattr(local_sgd_ops, "reduce", fake_reduce)
    state = _FakeState({"w": jnp.ones((4,))})
    for i in range(1, 10):
        state = sgd.step(state)
        assert len(calls) == i // 3
    assert all(c == "mean" for c in calls)
    # params re-committed to device arrays with preserved structure
    assert isinstance(state.params["w"], jax.Array)


def test_local_sgd_sync_bare_pytree(monkeypatch):
    monkeypatch.setattr(
        local_sgd_ops, "reduce", lambda p, reduction="mean": jax.tree.map(np.asarray, p)
    )
    sgd = LocalSGD(local_sgd_steps=1)
    sgd.enabled = True
    out = sgd.sync({"a": jnp.arange(3.0)})
    np.testing.assert_allclose(np.asarray(out["a"]), [0, 1, 2])


def test_local_sgd_rejects_bad_steps():
    with pytest.raises(ValueError, match="local_sgd_steps"):
        LocalSGD(local_sgd_steps=0)


def test_local_sgd_context_manager():
    with LocalSGD(local_sgd_steps=4) as sgd:
        assert sgd.num_steps == 0


def test_local_sgd_warns_on_mid_cadence_exit(monkeypatch):
    monkeypatch.setattr(
        local_sgd_ops, "reduce", lambda p, reduction="mean": jax.tree.map(np.asarray, p)
    )
    with pytest.warns(UserWarning, match="divergent"):
        with LocalSGD(local_sgd_steps=4) as sgd:
            sgd.enabled = True
            sgd.step({"w": jnp.ones(2)})
    # trailing sync() suppresses the warning
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        with LocalSGD(local_sgd_steps=4) as sgd:
            sgd.enabled = True
            state = sgd.step({"w": jnp.ones(2)})
            sgd.sync(state)


def test_unwrap_model_delegates_to_extract():
    from accelerate_tpu.accelerator import Accelerator
    acc = Accelerator()
    assert acc.unwrap_model("plain") == "plain"


def test_save_load_roundtrip_msgpack(tmp_path):
    tree = {"a": jnp.arange(6.0).reshape(2, 3), "b": {"c": jnp.ones((4,), jnp.bfloat16)}}
    path = tmp_path / "tree.msgpack"
    save(tree, path, safe_serialization=False)
    restored = load(path, target=tree)
    assert restored["b"]["c"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(restored["a"]), np.asarray(tree["a"]))
    # structural load without target
    raw = load(path)
    assert "a" in raw and "b" in raw


def test_save_load_roundtrip_safetensors(tmp_path):
    tree = {"a": jnp.arange(6.0).reshape(2, 3), "b": {"c": jnp.ones((4,), jnp.bfloat16)}}
    path = tmp_path / "tree.safetensors"
    save(tree, path)  # safe_serialization=True default
    flat = load(path)
    assert set(flat) == {"a", "b/c"}
    np.testing.assert_allclose(flat["a"], np.asarray(tree["a"]))
    assert flat["b/c"].dtype == np.dtype("bfloat16") or str(flat["b/c"].dtype) == "bfloat16"


def test_extract_model_passthrough_and_unwrap():
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
    from accelerate_tpu.parallel.pipeline_parallel import PipelinedModel
    from accelerate_tpu import ParallelismConfig

    assert extract_model_from_parallel("not a model") == "not a model"

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((4, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    mesh = ParallelismConfig(pp_size=2, dp_shard_size=4).build_device_mesh(jax.devices())
    pmodel = PipelinedModel(model, params, mesh, num_microbatches=2)
    assert extract_model_from_parallel(pmodel) is model


def test_aot_compile_and_regions():
    fn = lambda x: x * 2 + 1  # noqa: E731
    x = jnp.arange(8.0)
    compiled, secs = aot_compile(fn, x)
    np.testing.assert_allclose(np.asarray(compiled(x)), np.asarray(x) * 2 + 1)
    assert secs >= 0
    out = compile_regions({"double": fn}, x)
    np.testing.assert_allclose(np.asarray(out["double"](x)), np.asarray(x) * 2 + 1)


def test_check_os_kernel_no_crash(caplog):
    with caplog.at_level(logging.WARNING):
        check_os_kernel()


def test_version_helpers():
    from accelerate_tpu.utils.versions import compare_versions, is_jax_version

    assert is_jax_version(">=", "0.4.0")
    assert not is_jax_version("<", "0.4.0")
    assert compare_versions("numpy", ">", "1.0.0")
    import pytest

    with pytest.raises(ValueError, match="operation"):
        compare_versions("numpy", "~", "1.0")


def test_tqdm_main_process_only():
    from accelerate_tpu.utils.tqdm import tqdm

    bar = tqdm(range(3), main_process_only=True)
    # single process: local main -> not disabled (checked before iteration
    # completes — tqdm flips disable on close)
    assert not bar.disable
    assert list(bar) == [0, 1, 2]
    import pytest

    with pytest.raises(ValueError, match="main_process_only"):
        tqdm(True, range(3))


def test_rich_helpers(monkeypatch):
    from accelerate_tpu.utils import rich as rich_mod

    # opt-in is env-gated (reference utils/imports.py:289)
    monkeypatch.delenv("ACCELERATE_ENABLE_RICH", raising=False)
    assert not rich_mod.rich_enabled()
    monkeypatch.setenv("ACCELERATE_ENABLE_RICH", "true")
    assert rich_mod.rich_enabled() == rich_mod.is_rich_available()
    if rich_mod.is_rich_available():
        assert rich_mod.install_rich_tracebacks() is True
        console = rich_mod.get_console()
        assert hasattr(console, "print")


def test_set_cpu_affinity_partitions_cores(monkeypatch):
    """Minimal NUMA/affinity analog (reference set_numa_affinity
    environment.py:323): co-located ranks split the visible cores without
    overlap; rank index wraps; no-op without sched_setaffinity."""
    import os

    from accelerate_tpu.utils import environment as env_mod

    if not hasattr(os, "sched_setaffinity"):
        import pytest

        pytest.skip("platform without sched_setaffinity")
    pinned = {}
    monkeypatch.setattr(env_mod.os, "sched_getaffinity", lambda pid: set(range(8)))
    monkeypatch.setattr(env_mod.os, "sched_setaffinity", lambda pid, cores: pinned.update({"cores": sorted(cores)}))
    monkeypatch.setenv("ACCELERATE_NUM_PROCESSES", "4")
    env_mod.set_cpu_affinity.cache_clear()
    # striped: remainder cores distribute, ranks stay disjoint
    env_mod.set_cpu_affinity(0)
    assert pinned["cores"] == [0, 4]
    env_mod.set_cpu_affinity(3)
    assert pinned["cores"] == [3, 7]
    env_mod.set_cpu_affinity(5)  # wraps: 5 % 4 = 1
    assert pinned["cores"] == [1, 5]
    # more ranks than cores: overflow ranks get ONE shared core, never the
    # whole mask back
    env_mod.set_cpu_affinity(10, total_local_processes=16)
    assert pinned["cores"] == [2]
    env_mod.set_cpu_affinity.cache_clear()


def test_launch_flag_transports_cpu_affinity():
    from accelerate_tpu.commands.config import LaunchConfig
    from accelerate_tpu.commands.launch import _merge_args_into_config, launch_command_parser
    from accelerate_tpu.utils.launch import config_env

    args = launch_command_parser().parse_args(["--enable_cpu_affinity", "x.py"])
    cfg = _merge_args_into_config(args, LaunchConfig())
    assert config_env(cfg)["ACCELERATE_CPU_AFFINITY"] == "1"
