"""Feature examples stay diff-minimal against the canonical complete script
(reference tests/test_examples.py::ExampleDifferenceTests, Makefile:66-67).

``complete_nlp_example.py`` is the one full-featured script; the flagship
``nlp_example.py`` and the NLP-skeleton by_feature scripts must be that
script minus features — after stripping docstrings/comments/blank lines,
every line of a subset script has to appear verbatim in the complete script,
up to a small per-script allowance of genuinely feature-divergent lines
(constructor kwargs, loop headers).  A refactor that touches one copy of the
shared skeleton but not the others fails here.
"""

import ast
from pathlib import Path

import pytest

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"
COMPLETE = EXAMPLES / "complete_nlp_example.py"


def normalized_lines(path: Path, only_training_function: bool = False) -> list[str]:
    """Source lines with docstrings, comments, blanks, and indentation gone.

    With ``only_training_function`` the comparison is restricted to the
    shared skeleton (module prelude + dataset helpers + training_function);
    each script's ``main``/argparse/demo-driver plumbing is legitimately its
    own.
    """
    src = path.read_text()
    tree = ast.parse(src)
    doc_lines: set[int] = set()
    skip_spans: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if (
                node.body
                and isinstance(node.body[0], ast.Expr)
                and isinstance(node.body[0].value, ast.Constant)
                and isinstance(node.body[0].value.value, str)
            ):
                doc = node.body[0]
                doc_lines.update(range(doc.lineno, doc.end_lineno + 1))
    if only_training_function:
        for node in tree.body:
            if isinstance(node, ast.FunctionDef) and node.name == "main":
                skip_spans.update(range(node.lineno, node.end_lineno + 1))
            if isinstance(node, ast.If):  # the __main__ guard
                skip_spans.update(range(node.lineno, node.end_lineno + 1))
    out = []
    for i, line in enumerate(src.splitlines(), 1):
        if i in doc_lines or i in skip_spans:
            continue
        if "#" in line:
            line = line.split("#")[0]
        line = line.strip()
        if line:
            out.append(line)
    return out


# scripts that are "complete minus features", with the lines where they
# legitimately diverge (the feature boundary itself): anything else missing
# from the complete script is drift.
SUBSET_SCRIPTS = {
    # script -> (canonical complete script, allowance)
    "nlp_example.py": ("complete_nlp_example.py", 8),
    "by_feature/checkpointing.py": ("complete_nlp_example.py", 6),
    "by_feature/tracking.py": ("complete_nlp_example.py", 12),
    "by_feature/gradient_accumulation.py": ("complete_nlp_example.py", 8),
    "cv_example.py": ("complete_cv_example.py", 10),
}

# the complete script must keep exercising every composed feature — a line
# dropped here means the canonical script silently lost a capability
REQUIRED_FEATURE_LINES = [
    "mixed_precision=args.mixed_precision,",                      # mixed precision
    "gradient_accumulation_steps=args.gradient_accumulation_steps,",  # accumulation
    'log_with="jsonl" if args.with_tracking else None,',          # tracking
    "accelerator.save_state(train_state=state)",                  # checkpointing
    "state = accelerator.load_state(train_state=state)",          # resume
    "scheduler = accelerator.prepare(schedule)",                  # LR schedule
    "scheduler.step()",
    "preds, refs = accelerator.gather_for_metrics((preds, batch[\"labels\"]))",  # metrics
    "accelerator.end_training()",
]


@pytest.mark.parametrize("script,target", sorted(SUBSET_SCRIPTS.items()))
def test_subset_scripts_do_not_drift(script, target):
    complete_name, allowance = target
    subset = normalized_lines(EXAMPLES / script, only_training_function=True)
    complete = set(normalized_lines(EXAMPLES / complete_name))
    missing = [l for l in subset if l not in complete]
    assert len(missing) <= allowance, (
        f"{script} drifted from {complete_name} — {len(missing)} lines "
        f"(allowance {allowance}) not found in the complete script:\n  "
        + "\n  ".join(missing)
    )
    # the shared skeleton must dominate: a rewrite that keeps under the
    # allowance by shrinking the script is also drift
    shared = len(subset) - len(missing)
    assert shared >= 0.7 * len(subset) and shared >= 25, (
        f"{script} shares only {shared}/{len(subset)} lines with "
        f"{complete_name}; the common skeleton has been rewritten"
    )


def test_complete_script_keeps_every_feature():
    lines = set(normalized_lines(COMPLETE))
    missing = [l for l in REQUIRED_FEATURE_LINES if l not in lines]
    assert not missing, (
        "complete_nlp_example.py lost feature lines:\n  " + "\n  ".join(missing)
    )
