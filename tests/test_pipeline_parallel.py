"""Pipeline parallelism (SURVEY §2.4 P7): GPipe schedule over the pp axis.

Parity model: reference prepare_pippy (inference.py:126) microbatch forward,
plus training-PP capability (reference reaches it only via Megatron).
Numerical ground truth: the plain (non-pipelined) model forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import ParallelismConfig
from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.models.llama import causal_lm_loss
from accelerate_tpu.parallel.pipeline_parallel import (
    PipelinedModel,
    pipeline_blocks,
    prepare_pipeline,
    stack_layer_params,
    unstack_layer_params,
)


def _tiny_model(num_layers=4, attn="native"):
    cfg = LlamaConfig.tiny(num_hidden_layers=num_layers, attn_implementation=attn)
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (8, 16)), jnp.int32)
    params = model.init(jax.random.key(0), ids[:, :8])
    return cfg, model, params, ids


def _mesh(pp=4, **kw):
    return ParallelismConfig(pp_size=pp, **kw).build_device_mesh(jax.devices())


def test_stack_unstack_roundtrip():
    cfg, model, params, _ = _tiny_model()
    stacked, rest = stack_layer_params(dict(params["params"]), cfg.num_hidden_layers)
    for leaf in jax.tree.leaves(stacked):
        assert leaf.shape[0] == cfg.num_hidden_layers
    rebuilt = unstack_layer_params(stacked, rest)
    orig, new = jax.tree.leaves(params["params"]), jax.tree.leaves(rebuilt)
    assert all(np.allclose(a, b) for a, b in zip(orig, new))


@pytest.mark.xfail(
    condition=not hasattr(jax, "shard_map"),
    reason="old jax (no jax.shard_map): partial_manual_kwargs degrades the "
           "pipeline region to fully-manual, and the bf16 forward drifts "
           "~1.5% of elements just past the 2e-2 parity tolerance — the "
           "schedule itself still runs and differentiates (tests below)",
    strict=False,
)
@pytest.mark.parametrize("num_microbatches", [2, 4, 8])
def test_pipeline_matches_plain_forward(num_microbatches):
    cfg, model, params, ids = _tiny_model(num_layers=4)
    mesh = _mesh(pp=4, dp_shard_size=2)
    expected = model.apply(params, ids)
    pmodel = prepare_pipeline(model, params, mesh, num_microbatches=num_microbatches)
    got = pmodel(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-2, rtol=2e-2)


@pytest.mark.slow
def test_pipeline_two_stages_with_tp():
    cfg, model, params, ids = _tiny_model(num_layers=4)
    mesh = _mesh(pp=2, tp_size=2, dp_shard_size=2)
    expected = model.apply(params, ids)
    pmodel = prepare_pipeline(model, params, mesh, num_microbatches=4)
    got = pmodel(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-2, rtol=2e-2)


def test_pipeline_blocks_differentiable():
    """grad through the GPipe schedule == grad through the plain layer stack."""
    cfg, model, params, ids = _tiny_model(num_layers=4)
    mesh = _mesh(pp=4, dp_shard_size=2)
    stacked, rest = stack_layer_params(dict(params["params"]), cfg.num_hidden_layers)
    block = LlamaForCausalLM.block_cls(cfg)
    b, t = 4, 16
    positions = jnp.broadcast_to(jnp.arange(t), (b // 2, t))
    x = jax.random.normal(jax.random.key(1), (b, t, cfg.hidden_size), cfg.dtype)

    def block_fn(lp, h):
        return block.apply({"params": lp}, h, positions)

    def piped_loss(stacked):
        out = pipeline_blocks(stacked, x, block_fn, mesh, num_microbatches=2)
        return jnp.mean(jnp.square(out.astype(jnp.float32)))

    def plain_loss(stacked):
        h = x
        for i in range(cfg.num_hidden_layers):
            lp = jax.tree.map(lambda a, i=i: a[i], stacked)
            mbs = jnp.split(h, 2, axis=0)
            h = jnp.concatenate(
                [block.apply({"params": lp}, mb, positions) for mb in mbs], axis=0
            )
        return jnp.mean(jnp.square(h.astype(jnp.float32)))

    g_pipe = jax.jit(jax.grad(piped_loss))(stacked)
    g_plain = jax.jit(jax.grad(plain_loss))(stacked)
    for a, b_ in zip(jax.tree.leaves(g_pipe), jax.tree.leaves(g_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=3e-2, rtol=3e-2)


@pytest.mark.slow
def test_pipeline_training_step_improves_loss():
    """End-to-end pipelined TRAINING: loss decreases over a few adamw steps."""
    cfg, model, params, ids = _tiny_model(num_layers=2)
    mesh = _mesh(pp=2, dp_shard_size=4)
    pmodel = PipelinedModel(model, params, mesh, num_microbatches=2)
    labels = ids

    tx = optax.adamw(1e-2)
    opt_state = tx.init((pmodel.stacked, pmodel.rest))

    @jax.jit
    def step(stacked, rest, opt_state):
        def loss_fn(stacked, rest):
            logits = pmodel._forward(stacked, rest, ids)
            return causal_lm_loss(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(stacked, rest)
        updates, opt_state = tx.update(grads, opt_state, (stacked, rest))
        stacked, rest = optax.apply_updates((stacked, rest), updates)
        return stacked, rest, opt_state, loss

    stacked, rest = pmodel.stacked, pmodel.rest
    losses = []
    for _ in range(5):
        stacked, rest, opt_state, loss = step(stacked, rest, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_pipeline_validates_divisibility():
    cfg, model, params, ids = _tiny_model(num_layers=4)
    mesh = _mesh(pp=4, dp_shard_size=2)
    pmodel = prepare_pipeline(model, params, mesh, num_microbatches=3)
    with pytest.raises(ValueError, match="divisible"):
        pmodel(ids)  # batch 8 % 3 != 0


def test_parallelism_config_pp_axis():
    cfg = ParallelismConfig(pp_size=2, dp_shard_size=-1, tp_size=2)
    mesh = cfg.build_device_mesh(jax.devices())
    assert cfg.dp_shard_size == 2
    assert mesh.shape["pp"] == 2
    assert cfg.non_data_parallel_size == 4  # tp * pp
    env = cfg.to_env()
    assert env["PARALLELISM_CONFIG_PP_SIZE"] == "2"


def test_parallelism_config_pp_env_roundtrip(monkeypatch):
    for k, v in ParallelismConfig(pp_size=4, dp_shard_size=2).to_env().items():
        monkeypatch.setenv(k, v)
    restored = ParallelismConfig.from_env()
    assert restored.pp_size == 4 and restored.dp_shard_size == 2
