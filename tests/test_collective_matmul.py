"""Ring collective-matmul (ops/collective_matmul.py): numeric parity of the
latency-hiding ring schedules against the XLA monolithic collectives, knob
resolution, fallback gating, and the TP train-step / Ulysses-boundary wiring.

CPU-mesh contract (the acceptance bar): collective-matmul on vs off agree
within dtype tolerance for both all-gather→matmul and matmul→reduce-scatter,
for unidirectional and bidirectional rings, under ``jit`` and inside the TP
train step — plus an exact-f32 fixed-point check for the unidirectional ring
(integer-valued operands sum exactly in any reduction order, so the ring's
reordered accumulation must be bit-equal)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from shard_map_compat import NO_CHECK, shard_map

from accelerate_tpu.ops.collective_matmul import (
    all_gather_matmul_monolithic,
    collective_matmul,
    collective_matmul_mode,
    dense_collective_matmul,
    make_collective_dense,
    matmul_reduce_scatter_monolithic,
    normalize_mode,
    ring_all_gather_matmul,
    ring_matmul_reduce_scatter,
    ring_supported,
    set_collective_matmul,
    tp_comm_accounting,
    ulysses_sp_boundary,
)

rng = np.random.default_rng(7)


@pytest.fixture
def tp_mesh():
    return Mesh(np.asarray(jax.devices()).reshape(8), ("tp",))


def _col_run(body, mesh, x, w):
    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "tp", None), P(None, "tp")),
        out_specs=P(None, None, "tp"), **NO_CHECK,
    )
    return np.asarray(jax.jit(f)(x, w))


def _row_run(body, mesh, x, w):
    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, "tp"), P("tp", None)),
        out_specs=P(None, "tp", None), **NO_CHECK,
    )
    return np.asarray(jax.jit(f)(x, w))


def _rand(shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


# ---------------------------------------------------------------------------
# ring bodies vs the monolithic collectives (the same shard_map layout)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bidirectional", [False, True])
def test_all_gather_matmul_ring_matches_monolithic(tp_mesh, bidirectional):
    x, w = _rand((2, 16, 8)), _rand((8, 24))
    ring = functools.partial(ring_all_gather_matmul, axis_name="tp",
                             bidirectional=bidirectional)
    mono = functools.partial(all_gather_matmul_monolithic, axis_name="tp")
    got = _col_run(ring, tp_mesh, x, w)
    want = _col_run(mono, tp_mesh, x, w)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got, np.asarray(x @ w), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("bidirectional", [False, True])
def test_matmul_reduce_scatter_ring_matches_monolithic(tp_mesh, bidirectional):
    x, w = _rand((2, 16, 8)), _rand((8, 24))
    ring = functools.partial(ring_matmul_reduce_scatter, axis_name="tp",
                             bidirectional=bidirectional)
    mono = functools.partial(matmul_reduce_scatter_monolithic, axis_name="tp")
    got = _row_run(ring, tp_mesh, x, w)
    want = _row_run(mono, tp_mesh, x, w)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got, np.asarray(x @ w), rtol=1e-4, atol=1e-5)


def test_unidirectional_ring_exact_f32_fixed_point(tp_mesh):
    # integer-valued f32: every partial sum is exactly representable, so the
    # unidirectional ring's reordered accumulation must be BIT-equal to the
    # monolithic result (the fixed-point contract from the issue)
    xi = jnp.asarray(rng.integers(-8, 9, (2, 16, 8)), jnp.float32)
    wi = jnp.asarray(rng.integers(-8, 9, (8, 24)), jnp.float32)
    ag = _col_run(functools.partial(ring_all_gather_matmul, axis_name="tp"), tp_mesh, xi, wi)
    rs = _row_run(functools.partial(ring_matmul_reduce_scatter, axis_name="tp"), tp_mesh, xi, wi)
    want = np.asarray(xi @ wi)
    assert np.array_equal(ag, want)
    assert np.array_equal(rs, want)


def test_ring_bodies_bf16_tolerance(tp_mesh):
    x, w = _rand((2, 16, 32), jnp.bfloat16), _rand((32, 24), jnp.bfloat16)
    got = _col_run(functools.partial(ring_all_gather_matmul, axis_name="tp"), tp_mesh, x, w)
    want = np.asarray(
        (x.astype(jnp.float32) @ w.astype(jnp.float32))
    )
    np.testing.assert_allclose(got.astype(np.float32), want, rtol=5e-2, atol=1e-1)


# ---------------------------------------------------------------------------
# global-array wrappers: jit, grads, preferred_element_type
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["ring", "bidir"])
@pytest.mark.parametrize("kind", ["column", "row"])
def test_make_collective_dense_parity_and_grads(tp_mesh, kind, mode):
    x, w = _rand((2, 16, 16)), _rand((16, 32))
    fn = make_collective_dense(tp_mesh, "tp", kind, mode)
    np.testing.assert_allclose(np.asarray(fn(x, w)), np.asarray(x @ w),
                               rtol=1e-5, atol=1e-5)

    def loss_ring(x, w):
        return jnp.sum(jnp.sin(fn(x, w)))

    def loss_ref(x, w):
        return jnp.sum(jnp.sin(x @ w))

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1)))(x, w)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(x, w)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_make_collective_dense_preferred_element_type(tp_mesh):
    x = _rand((2, 8, 16), jnp.bfloat16)
    w = _rand((16, 32), jnp.bfloat16)
    fn = make_collective_dense(tp_mesh, "tp", "column", "ring",
                               preferred_element_type=jnp.float32)
    out = fn(x, w)
    assert out.dtype == jnp.float32


# ---------------------------------------------------------------------------
# knob resolution + gating
# ---------------------------------------------------------------------------


def test_mode_normalization_and_env(monkeypatch):
    assert normalize_mode("on") == "ring"
    assert normalize_mode("BIDIRECTIONAL") == "bidir"
    assert normalize_mode("off") == "off"
    with pytest.raises(ValueError):
        normalize_mode("sideways")
    monkeypatch.setenv("ACCELERATE_COLLECTIVE_MATMUL", "on")
    assert collective_matmul_mode() == "ring"
    monkeypatch.delenv("ACCELERATE_COLLECTIVE_MATMUL")
    assert collective_matmul_mode() == "off"
    prev = set_collective_matmul("bidir")
    try:
        assert collective_matmul_mode() == "bidir"
        with collective_matmul("off"):
            assert collective_matmul_mode() == "off"
        assert collective_matmul_mode() == "bidir"
    finally:
        set_collective_matmul(prev)


def test_plugin_knob_normalizes_and_installs(monkeypatch):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    monkeypatch.setenv("ACCELERATE_COLLECTIVE_MATMUL", "bidir")
    assert FullyShardedDataParallelPlugin().collective_matmul == "bidir"
    monkeypatch.delenv("ACCELERATE_COLLECTIVE_MATMUL")
    plugin = FullyShardedDataParallelPlugin(collective_matmul="on")
    assert plugin.collective_matmul == "ring"
    with pytest.raises(ValueError):
        FullyShardedDataParallelPlugin(collective_matmul="sideways")
    # the Accelerator installs the plugin knob as the ambient mode
    Accelerator(fsdp_plugin=plugin)
    assert collective_matmul_mode() == "ring"


def test_plugin_less_accelerator_clears_stale_override():
    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState
    from accelerate_tpu.utils.dataclasses import FullyShardedDataParallelPlugin

    Accelerator(fsdp_plugin=FullyShardedDataParallelPlugin(collective_matmul="ring"))
    assert collective_matmul_mode() == "ring"
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    # the old accelerator's ambient mode must not leak into the next one
    assert collective_matmul_mode() == "off"
    Accelerator()
    assert collective_matmul_mode() == "off"


def test_ring_supported_gating(tp_mesh):
    assert ring_supported(tp_mesh, "tp")
    assert not ring_supported(tp_mesh, "sp")       # axis absent
    assert not ring_supported(None, "tp")
    one = Mesh(np.asarray(jax.devices()[:1]).reshape(1), ("tp",))
    assert not ring_supported(one, "tp")           # trivial ring
    if not hasattr(jax, "shard_map"):
        # old-jax compat: fully-manual degradation only exact when every
        # other axis is trivial — multi-axis meshes must fall back
        multi = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("dp_shard", "tp"))
        assert not ring_supported(multi, "tp")


def test_dense_hook_fallbacks(monkeypatch):
    from accelerate_tpu import Accelerator, ParallelismConfig

    Accelerator(parallelism_config=ParallelismConfig(tp_size=8))
    x, w = _rand((2, 16, 16)), _rand((16, 32))
    # off -> None regardless of mesh
    assert dense_collective_matmul(x, w, "column") is None
    with collective_matmul("ring"):
        y = dense_collective_matmul(x, w, "column")
        assert y is not None
        np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), rtol=1e-5, atol=1e-5)
        # non-dividing shapes fall back
        assert dense_collective_matmul(_rand((2, 15, 16)), w, "column") is None  # T % 8
        assert dense_collective_matmul(x, _rand((16, 30)), "column") is None     # N % 8
        assert dense_collective_matmul(x, _rand((15, 32))[:15], "row") is None   # K mismatch
        assert dense_collective_matmul(x[:, 0], w, "column") is None             # 2D input
        assert dense_collective_matmul(x, w, "replicated") is None               # bad kind


def test_dense_hook_without_accelerator_state_is_none():
    x, w = _rand((2, 16, 16)), _rand((16, 32))
    with collective_matmul("ring"):
        assert dense_collective_matmul(x, w, "column") is None


# ---------------------------------------------------------------------------
# wiring: TP train step and the Ulysses sp boundary
# ---------------------------------------------------------------------------


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()


def _train_losses(mode, pcfg, attn="native", kv_heads=2, steps=3):
    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM, make_llama_loss_fn

    _reset_state()
    acc = Accelerator(parallelism_config=pcfg)
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attn_implementation=attn,
                           num_key_value_heads=kv_heads)
    model = LlamaForCausalLM(cfg)
    tokens = np.random.default_rng(3).integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    ids = jnp.asarray(tokens)
    batch = {"input_ids": ids, "labels": ids}
    with collective_matmul(mode):
        params = model.init(jax.random.key(0), ids[:, :8])
        state = acc.create_train_state(params, optax.adam(1e-2), apply_fn=model.apply)
        step = acc.prepare_train_step(make_llama_loss_fn(model), max_grad_norm=1.0)
        losses = []
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    return losses


def _jaxpr_prims(closed):
    from accelerate_tpu.analysis import iter_eqns

    return {eqn.primitive.name for eqn in iter_eqns(closed)}


@pytest.mark.parametrize("mode", ["ring", "bidir"])
def test_tp_train_step_parity(mode):
    from accelerate_tpu import ParallelismConfig

    off = _train_losses("off", ParallelismConfig(tp_size=8))
    on = _train_losses(mode, ParallelismConfig(tp_size=8))
    assert all(np.isfinite(off)) and all(np.isfinite(on))
    np.testing.assert_allclose(on, off, rtol=2e-4)
    assert off[-1] < off[0]  # the step actually trains


def test_tp_forward_ring_engages():
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    _reset_state()
    Accelerator(parallelism_config=ParallelismConfig(tp_size=8))
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((2, 32), jnp.int32)
    with collective_matmul("ring"):
        params = model.init(jax.random.key(0), ids[:, :8])
        prims_on = _jaxpr_prims(jax.jit(model.apply).trace(params, ids).jaxpr)
    prims_off = _jaxpr_prims(jax.jit(model.apply).trace(params, ids).jaxpr)
    assert "ppermute" in prims_on
    assert "ppermute" not in prims_off


def test_ulysses_sp_boundary_parity_and_alltoall_elision():
    from accelerate_tpu import Accelerator, ParallelismConfig
    from accelerate_tpu.models import LlamaConfig, LlamaForCausalLM

    sp_cfg = lambda: ParallelismConfig(sp_size=4, devices=tuple(jax.devices()[:4]))
    off = _train_losses("off", sp_cfg(), attn="ulysses", kv_heads=4)
    on = _train_losses("ring", sp_cfg(), attn="ulysses", kv_heads=4)
    np.testing.assert_allclose(on, off, rtol=2e-4)

    # the boundary really replaced the monolithic all_to_alls with rings
    _reset_state()
    Accelerator(parallelism_config=sp_cfg())
    cfg = LlamaConfig.tiny(dtype=jnp.float32, attn_implementation="ulysses",
                           num_key_value_heads=4)
    model = LlamaForCausalLM(cfg)
    ids = jnp.ones((2, 32), jnp.int32)
    with collective_matmul("ring"):
        params = model.init(jax.random.key(0), ids[:, :8])
        prims_on = _jaxpr_prims(jax.jit(model.apply).trace(params, ids).jaxpr)
    prims_off = _jaxpr_prims(jax.jit(model.apply).trace(params, ids).jaxpr)
    assert "all_to_all" in prims_off and "ppermute" not in prims_off
    assert "ppermute" in prims_on and "all_to_all" not in prims_on


def test_ulysses_sp_boundary_gating():
    from accelerate_tpu import Accelerator, ParallelismConfig

    _reset_state()
    Accelerator(parallelism_config=ParallelismConfig(sp_size=4, devices=tuple(jax.devices()[:4])))
    assert not ulysses_sp_boundary(4, 4, 32)  # mode off
    with collective_matmul("ring"):
        assert ulysses_sp_boundary(4, 4, 32)
        assert not ulysses_sp_boundary(6, 4, 32)  # heads % sp
        assert not ulysses_sp_boundary(4, 2, 32)  # kv heads % sp
        assert not ulysses_sp_boundary(4, 4, 30)  # seq % sp
    _reset_state()
    # composed sp x tp keeps the all_to_all path (kernel dims can't be
    # manual over sp and auto over tp at once)
    from accelerate_tpu import Accelerator as Acc

    Acc(parallelism_config=ParallelismConfig(sp_size=2, tp_size=2,
                                             devices=tuple(jax.devices()[:4])))
    with collective_matmul("ring"):
        assert not ulysses_sp_boundary(4, 4, 32)


# ---------------------------------------------------------------------------
# overlap accounting
# ---------------------------------------------------------------------------


def test_tp_comm_accounting_envelope():
    rep = tp_comm_accounting(8 * 2048, 4096, 11008, 4)
    assert rep["kind"] == "predicted"
    assert 0.0 <= rep["tp_overlap_frac"] <= 1.0
    assert rep["steps"] == 3 and rep["ring_size"] == 4
    bi = tp_comm_accounting(8 * 2048, 4096, 11008, 4, bidirectional=True)
    assert bi["steps"] == 2  # ceil((p-1)/2): halved ring depth
    # trivial ring: nothing to hide, nothing to report
    triv = tp_comm_accounting(8 * 2048, 4096, 11008, 1)
    assert triv["steps"] == 0 and triv["tp_overlap_frac"] == 0.0
    # a wire-starved shape (tiny matmul over a slow link) cannot hide its hops
    starved = tp_comm_accounting(64, 64, 64, 8, ici_gibs=1e-3)
    assert starved["tp_overlap_frac"] < 1.0


def test_stream_stats_ici_fields():
    from accelerate_tpu.ops.streaming import StreamStats

    stats = StreamStats()
    rep = stats.overlap_report()
    assert "ici_bytes" not in rep and "tp_overlap_frac" not in rep  # key set stable
    stats.ici_bytes = 1024
    stats.tp_overlap_frac = 0.75
    rep = stats.overlap_report()
    assert rep["ici_bytes"] == 1024 and rep["tp_overlap_frac"] == 0.75


def test_ici_overlap_report_from_cpu_trace(tmp_path):
    from accelerate_tpu.utils.xplane import ici_overlap_report

    @jax.jit
    def f(x):
        return jnp.sin(x) @ jnp.cos(x).T

    x = _rand((64, 64))
    f(x).block_until_ready()
    jax.profiler.start_trace(str(tmp_path))
    f(x).block_until_ready()
    jax.profiler.stop_trace()
    rep = ici_overlap_report(str(tmp_path), "CPU")
    for field in ("collective_ms_inline", "collective_ms_async",
                  "collective_occupancy", "tp_overlap_frac", "kind"):
        assert field in rep, field
    assert rep["kind"] == "measured"
    assert rep["tp_overlap_frac"] == 0.0  # no collectives in this trace
