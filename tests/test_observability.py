"""Trackers / logging / memory-util tests (reference tests/test_tracking.py +
test_memory_utils.py coverage)."""

import json
import logging

import jax
import numpy as np
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.logging import get_logger
from accelerate_tpu.tracking import JSONLTracker, filter_trackers, resolve_tracker
from accelerate_tpu.utils.memory import (
    find_executable_batch_size,
    get_device_memory_stats,
    release_memory,
    should_reduce_batch_size,
)


def test_jsonl_tracker_roundtrip(tmp_path):
    tracker = JSONLTracker("run1", logging_dir=str(tmp_path))
    tracker.store_init_configuration({"lr": 0.1, "nested": {"a": 1}})
    tracker.log({"loss": 1.5}, step=0)
    tracker.log({"loss": 1.0}, step=1)
    cfg = json.loads((tmp_path / "run1" / "config.json").read_text())
    assert cfg["lr"] == 0.1
    lines = [json.loads(l) for l in (tmp_path / "run1" / "metrics.jsonl").read_text().splitlines()]
    assert [l["loss"] for l in lines] == [1.5, 1.0]
    assert lines[1]["_step"] == 1


def test_jsonl_tracker_survives_sigkill_without_torn_lines(tmp_path):
    """The torn-line hardening witness: a writer subprocess is SIGKILLed
    mid-stream, and EVERY line in the survivor file must still parse as a
    complete JSON record (whole-line unbuffered writes + atexit close — the
    checkpointing atomicity discipline applied to metrics).  Lines may be
    missing at the tail; none may be torn."""
    import os
    import signal
    import subprocess
    import sys
    import time as _time

    script = (
        "import sys\n"
        "from accelerate_tpu.tracking import JSONLTracker\n"
        "t = JSONLTracker('killed', logging_dir=sys.argv[1])\n"
        "print('ready', flush=True)\n"
        "i = 0\n"
        "while True:\n"
        "    t.log({'step_metric': i, 'payload': 'x' * 200}, step=i)\n"
        "    i += 1\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", script, str(tmp_path)],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert proc.stdout.readline().strip() == "ready"
        path = tmp_path / "killed" / "metrics.jsonl"
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            if path.exists() and path.stat().st_size > 20_000:
                break
            _time.sleep(0.01)
        else:
            raise AssertionError("writer never produced enough lines")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    raw = path.read_bytes()
    lines = raw.split(b"\n")
    assert len(lines) > 20
    # a torn final line would fail json.loads; every line must be complete
    for i, line in enumerate(lines):
        if not line:
            continue
        rec = json.loads(line)
        assert rec["step_metric"] == rec["_step"]
    # the file ends ON a line boundary (the last byte written was a full
    # record's newline — nothing half-flushed)
    assert raw.endswith(b"\n")


def test_jsonl_tracker_logs_after_finish(tmp_path):
    """Stragglers after finish() still land (reopen-per-line fallback) —
    end_training followed by a late log must not crash or tear."""
    tracker = JSONLTracker("late", logging_dir=str(tmp_path))
    tracker.log({"a": 1}, step=0)
    tracker.finish()
    tracker.log({"a": 2}, step=1)
    lines = [json.loads(l) for l in
             (tmp_path / "late" / "metrics.jsonl").read_text().splitlines()]
    assert [l["a"] for l in lines] == [1, 2]


def test_accelerator_tracker_glue(tmp_path):
    acc = Accelerator(log_with="jsonl", project_dir=str(tmp_path))
    acc.init_trackers("proj", config={"bs": 8})
    acc.log({"loss": 0.5}, step=0)
    tracker = acc.get_tracker("jsonl")
    assert tracker is not None
    acc.end_training()
    assert (tmp_path / "proj" / "metrics.jsonl").exists()


def test_filter_trackers_unknown_raises():
    with pytest.raises(ValueError):
        filter_trackers("definitely_not_a_tracker")


def test_multiprocess_logger(caplog):
    logger = get_logger("accelerate_tpu.test")
    with caplog.at_level(logging.INFO, logger="accelerate_tpu.test"):
        logger.info("hello", main_process_only=True)
    assert any("hello" in r.message for r in caplog.records)


def test_find_executable_batch_size(monkeypatch):
    # stub the real cache clear: wiping the global jit cache mid-suite makes
    # every later test recompile (measured ~11 s of collateral); asserting
    # the call count keeps the behavior pinned without the poison
    cleared = []
    from accelerate_tpu.utils import memory as memory_mod

    monkeypatch.setattr(memory_mod.jax, "clear_caches", lambda: cleared.append(1))
    attempts = []

    @find_executable_batch_size(starting_batch_size=64)
    def train(batch_size):
        attempts.append(batch_size)
        if batch_size > 16:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating")
        return batch_size

    assert train() == 16
    assert attempts == [64, 32, 16]
    assert len(cleared) == 2  # one clear per OOM retry


def test_find_executable_batch_size_non_oom_propagates():
    @find_executable_batch_size(starting_batch_size=8)
    def train(batch_size):
        raise ValueError("unrelated")

    with pytest.raises(ValueError, match="unrelated"):
        train()


def test_find_executable_batch_size_signature_check():
    @find_executable_batch_size(starting_batch_size=8)
    def train(foo):
        return foo

    with pytest.raises(TypeError, match="batch_size"):
        train()


def test_should_reduce_batch_size():
    assert should_reduce_batch_size(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert should_reduce_batch_size(MemoryError())
    assert not should_reduce_batch_size(ValueError("nope"))


def test_release_memory():
    a, b = np.ones(10), np.ones(10)
    a, b = release_memory(a, b)
    assert a is None and b is None


def test_device_memory_stats():
    stats = get_device_memory_stats()
    assert set(stats) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}


class _Recorder:
    """Attribute sink: every call lands in the shared list as
    (dotted.name, args, kwargs); attribute access nests, so both
    ``run.log(...)`` and ``run.config.update(...)`` record."""

    def __init__(self, calls, prefix=""):
        self._calls, self._prefix = calls, prefix.rstrip(".")

    def __getattr__(self, name):
        dot = "." if self._prefix else ""
        return _Recorder(self._calls, f"{self._prefix}{dot}{name}")

    def __call__(self, *args, **kwargs):
        self._calls.append((self._prefix, args, kwargs))
        return _Recorder(self._calls, self._prefix + "()")

    def __setitem__(self, key, value):
        self._calls.append(("__setitem__", (key, value), {}))


def test_wandb_tracker_contract(monkeypatch):
    """Backend-contract pin via an injected fake module (VERDICT r3 weak #5):
    the wandb tracker must call init(project=...), config.update, run.log
    with step, and run.finish — the call shapes real wandb exposes."""
    import sys
    import types

    calls = []
    fake = types.ModuleType("wandb")
    fake.init = lambda project=None, **kw: calls.append(("init", project, kw)) or _Recorder(calls, "run.")
    fake.config = _Recorder(calls, "config.")
    monkeypatch.setitem(sys.modules, "wandb", fake)
    from accelerate_tpu.tracking import WandBTracker

    t = WandBTracker("proj")
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.0}, step=3)
    t.finish()
    assert calls[0] == ("init", "proj", {})
    assert ("config.update", ({"lr": 0.1},), {"allow_val_change": True}) in calls
    assert ("run.log", ({"loss": 1.0},), {"step": 3}) in calls
    assert calls[-1][0] == "run.finish"


def test_mlflow_tracker_contract(monkeypatch):
    import sys
    import types

    calls = []
    fake = types.ModuleType("mlflow")
    rec = _Recorder(calls)
    for name in ("set_experiment", "start_run", "log_param", "log_metrics", "end_run"):
        setattr(fake, name, getattr(rec, name))
    monkeypatch.setitem(sys.modules, "mlflow", fake)
    from accelerate_tpu.tracking import MLflowTracker

    t = MLflowTracker("exp")
    t.store_init_configuration({"opt": {"lr": 0.1}})
    t.log({"loss": 2.0, "note": "str-dropped"}, step=7)
    t.finish()
    names = [c[0] for c in calls]
    assert names[:2] == ["set_experiment", "start_run"]
    assert ("log_param", ("opt.lr", 0.1), {}) in calls
    assert ("log_metrics", ({"loss": 2.0},), {"step": 7}) in calls
    assert names[-1] == "end_run"


def test_comet_tracker_contract(monkeypatch):
    import sys
    import types

    calls = []
    fake = types.ModuleType("comet_ml")
    fake.Experiment = lambda project_name=None, **kw: calls.append(
        ("Experiment", project_name, kw)
    ) or _Recorder(calls, "exp.")
    monkeypatch.setitem(sys.modules, "comet_ml", fake)
    from accelerate_tpu.tracking import CometMLTracker

    t = CometMLTracker("proj")
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 0.5}, step=2)
    t.finish()
    assert calls[0] == ("Experiment", "proj", {})
    assert ("exp.log_parameters", ({"lr": 0.1},), {}) in calls
    assert ("exp.set_step", (2,), {}) in calls
    assert ("exp.log_metrics", ({"loss": 0.5},), {"step": 2}) in calls
    assert calls[-1][0] == "exp.end"


def test_aim_tracker_contract(monkeypatch, tmp_path):
    import sys
    import types

    calls = []
    fake = types.ModuleType("aim")
    fake.Run = lambda repo=None, experiment=None, **kw: calls.append(
        ("Run", repo, experiment)
    ) or _Recorder(calls, "run.")
    monkeypatch.setitem(sys.modules, "aim", fake)
    from accelerate_tpu.tracking import AimTracker

    t = AimTracker("exp", logging_dir=str(tmp_path))
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.0}, step=4)
    t.finish()
    assert calls[0] == ("Run", str(tmp_path), "exp")
    assert ("__setitem__", ("hparams", {"lr": 0.1}), {}) in calls
    assert ("run.track", (1.0,), {"name": "loss", "step": 4}) in calls
    assert calls[-1][0] == "run.close"


def test_clearml_tracker_contract(monkeypatch):
    import sys
    import types

    calls = []
    fake = types.ModuleType("clearml")

    class _Task:
        @staticmethod
        def init(project_name=None, **kw):
            calls.append(("Task.init", project_name))
            return _Recorder(calls, "task.")

    fake.Task = _Task
    monkeypatch.setitem(sys.modules, "clearml", fake)
    from accelerate_tpu.tracking import ClearMLTracker

    t = ClearMLTracker("proj")
    t.store_init_configuration({"lr": 0.1})
    t.log({"train/loss": 2.0}, step=5)
    t.finish()
    assert calls[0] == ("Task.init", "proj")
    assert ("task.connect_configuration", ({"lr": 0.1},), {}) in calls
    # the logger object comes from task.get_logger(); report_scalar splits
    # "train/loss" into title/series
    assert ("task.get_logger().report_scalar", (), {
        "title": "train", "series": "loss", "value": 2.0, "iteration": 5,
    }) in calls
    assert calls[-1][0] == "task.close"


def test_dvclive_tracker_contract(monkeypatch):
    import sys
    import types

    calls = []
    fake = types.ModuleType("dvclive")
    fake.Live = lambda **kw: _Recorder(calls, "live.")
    monkeypatch.setitem(sys.modules, "dvclive", fake)
    from accelerate_tpu.tracking import DVCLiveTracker

    t = DVCLiveTracker("run")
    t.store_init_configuration({"opt": {"lr": 0.1}})
    t.log({"loss": 3.0})
    t.finish()
    assert ("live.log_params", ({"opt.lr": 0.1},), {}) in calls
    assert ("live.log_metric", ("loss", 3.0), {}) in calls
    assert [c[0] for c in calls if c[0] == "live.next_step"]
    assert calls[-1][0] == "live.end"


def test_swanlab_and_trackio_tracker_contracts(monkeypatch):
    import sys
    import types

    for mod_name, tracker_name in [("swanlab", "SwanLabTracker"), ("trackio", "TrackioTracker")]:
        calls = []
        fake = types.ModuleType(mod_name)
        fake.init = lambda project=None, **kw: calls.append(("init", project)) or _Recorder(calls, "run.")
        fake.config = _Recorder(calls, "config.")
        fake.log = lambda values, **kw: calls.append(("log", values))
        fake.finish = lambda: calls.append(("finish", None))
        monkeypatch.setitem(sys.modules, mod_name, fake)
        import accelerate_tpu.tracking as tracking_mod

        t = getattr(tracking_mod, tracker_name)("proj")
        t.store_init_configuration({"lr": 0.1})
        t.log({"loss": 1.5}, step=1)
        t.finish()
        assert calls[0] == ("init", "proj"), (mod_name, calls)
        assert any("loss" in str(c) for c in calls), (mod_name, calls)


def test_tensorboard_tracker_contract(monkeypatch, tmp_path):
    import sys
    import types

    calls = []
    tb = types.ModuleType("torch.utils.tensorboard")
    tb.SummaryWriter = lambda d, **kw: calls.append(("SummaryWriter", d)) or _Recorder(calls, "w.")
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", tb)
    import torch.utils as tu

    monkeypatch.setattr(tu, "tensorboard", tb, raising=False)
    from accelerate_tpu.tracking import TensorBoardTracker

    t = TensorBoardTracker("run1", logging_dir=str(tmp_path))
    t.store_init_configuration({"lr": 0.1})
    t.log({"loss": 1.0, "note": "hi"}, step=2)
    t.finish()
    assert calls[0][0] == "SummaryWriter" and calls[0][1].endswith("run1")
    assert ("w.add_hparams", ({"lr": 0.1},), {"metric_dict": {}}) in calls
    assert ("w.add_scalar", ("loss", 1.0), {"global_step": 2}) in calls
    assert ("w.add_text", ("note", "hi"), {"global_step": 2}) in calls
    assert calls[-1][0] == "w.close"


def test_profile_context(tmp_path):
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    acc = Accelerator()
    handler = ProfileKwargs(output_trace_dir=str(tmp_path / "trace"))
    with acc.profile(handler):
        jax.numpy.ones(8).sum()
    assert (tmp_path / "trace").exists()


def test_profiler_streaming_overlap_report(tmp_path):
    """The profiler-side overlap accounting (transfer-vs-compute occupancy
    + achieved overlap_frac) decodes from a real captured trace and carries
    the full field set; occupancies are valid shares."""
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    acc = Accelerator()
    handler = ProfileKwargs(output_trace_dir=str(tmp_path / "trace"))
    with acc.profile(handler) as p:
        jax.block_until_ready(jax.jit(lambda x: (x @ x).sum())(jax.numpy.ones((64, 64))))
    rep = p.streaming_overlap(device_substr="CPU")
    for field in ("total_ms", "copy_ms_inline", "copy_ms_async",
                  "host_compute_ms", "transfer_occupancy", "host_occupancy",
                  "compute_occupancy", "overlap_frac"):
        assert field in rep, field
    assert rep["kind"] == "measured"
    for share in ("transfer_occupancy", "host_occupancy", "compute_occupancy",
                  "overlap_frac"):
        assert 0.0 <= rep[share] <= 1.0
    # no trace dir -> loud error, matching key_averages
    from accelerate_tpu.utils.profiler import TPUProfiler

    bare = TPUProfiler(ProfileKwargs())
    with pytest.raises(ValueError):
        bare.streaming_overlap()


def _windowed_profiler(monkeypatch, handler):
    """TPUProfiler with trace start/stop spied into an event list."""
    from accelerate_tpu.utils import profiler as prof_mod

    events = []
    monkeypatch.setattr(
        prof_mod.jax.profiler, "start_trace",
        lambda d, **kw: events.append(("start", d)),
    )
    monkeypatch.setattr(
        prof_mod.jax.profiler, "stop_trace", lambda: events.append(("stop", None))
    )
    return prof_mod.TPUProfiler(handler), events


def test_profile_schedule_exact_window(monkeypatch, tmp_path):
    """Exactly steps [wait+warmup, wait+warmup+active) are traced."""
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    handler = ProfileKwargs(wait=2, warmup=1, active=3, repeat=1,
                            output_trace_dir=str(tmp_path))
    profiler, events = _windowed_profiler(monkeypatch, handler)
    profiler._enter()
    for _ in range(10):
        profiler.step()
    profiler._exit()
    assert profiler.summary["traced_steps"] == [3, 4, 5]
    assert events == [("start", str(tmp_path)), ("stop", None)]
    assert profiler.summary["cycles"] == 1


def test_profile_schedule_repeat_cycles(monkeypatch, tmp_path):
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    ready_dirs = []
    handler = ProfileKwargs(wait=1, warmup=0, active=1, repeat=2,
                            output_trace_dir=str(tmp_path),
                            on_trace_ready=ready_dirs.append)
    profiler, events = _windowed_profiler(monkeypatch, handler)
    profiler._enter()
    for _ in range(6):
        profiler.step()
    profiler._exit()
    # cycle length 2: active steps are 1 and 3; repeat=2 stops after cycle 2
    assert profiler.summary["traced_steps"] == [1, 3]
    assert [e[0] for e in events] == ["start", "stop", "start", "stop"]
    # cycle 0 keeps the configured dir (pre-schedule layout); later cycles nest
    assert ready_dirs == [str(tmp_path), str(tmp_path / "cycle_1")]


def test_profile_bare_block_traces_whole_region(monkeypatch, tmp_path):
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    handler = ProfileKwargs(output_trace_dir=str(tmp_path))
    profiler, events = _windowed_profiler(monkeypatch, handler)
    profiler._enter()
    profiler._exit()
    assert [e[0] for e in events] == ["start", "stop"]
    assert profiler.summary["traced_steps"] == [0]


def test_profile_no_schedule_is_one_continuous_window(monkeypatch, tmp_path):
    """All-defaults ProfileKwargs + per-step step() = ONE window for the whole
    block (the reference's no-schedule torch.profiler behavior), not a
    start/stop pair and cycle_<i> dir per training step (ADVICE r4)."""
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    handler = ProfileKwargs(output_trace_dir=str(tmp_path))
    profiler, events = _windowed_profiler(monkeypatch, handler)
    profiler._enter()
    for _ in range(5):
        profiler.step()
    profiler._exit()
    assert [e[0] for e in events] == ["start", "stop"]
    assert profiler.summary["cycles"] == 1
    assert profiler.summary["traced_steps"] == [0, 1, 2, 3, 4]


def test_profile_explicit_active_one_still_cycles(monkeypatch, tmp_path):
    """An EXPLICIT active=1 keeps per-cycle windows — only the untouched
    default is treated as 'no schedule'."""
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    handler = ProfileKwargs(active=1, output_trace_dir=str(tmp_path))
    profiler, events = _windowed_profiler(monkeypatch, handler)
    profiler._enter()
    for _ in range(3):
        profiler.step()
    profiler._exit()
    assert [e[0] for e in events] == ["start"] + ["stop", "start"] * 3 + ["stop"]
    assert profiler.summary["cycles"] == 4


def test_profile_explicit_active_zero_rejected():
    from accelerate_tpu.utils.dataclasses import ProfileKwargs

    with pytest.raises(ValueError, match="active"):
        ProfileKwargs(active=0)


def test_profile_memory_and_flops():
    from accelerate_tpu.utils.dataclasses import ProfileKwargs
    from accelerate_tpu.utils.profiler import TPUProfiler

    handler = ProfileKwargs(profile_memory=True, with_flops=True)  # no trace dir
    profiler = TPUProfiler(handler)
    profiler._enter()
    flops = profiler.flops_estimate(lambda x: x @ x, np.ones((32, 32), np.float32))
    profiler._exit()
    assert flops > 0
    assert profiler.summary["flops"] == flops
    mem = profiler.summary["memory"]
    assert {"bytes_in_use", "bytes_delta", "peak_bytes_in_use", "bytes_limit"} <= set(mem)


def test_profiler_key_averages_from_trace(tmp_path):
    """key_averages (torch profiler table analog): capture a real trace,
    decode the xplane artifact in-process, shares sum to 1."""
    import jax.numpy as jnp

    from accelerate_tpu.utils.dataclasses import ProfileKwargs
    from accelerate_tpu.utils.profiler import TPUProfiler

    f = jax.jit(lambda x: jnp.tanh(x @ x).sum())
    x = np.ones((256, 256), np.float32)
    float(f(x))  # compile outside the window
    prof = TPUProfiler(ProfileKwargs(output_trace_dir=str(tmp_path)))
    prof._enter()
    for _ in range(2):
        float(f(x))
    prof._exit()
    table = prof.key_averages(device_substr="CPU")
    assert table["_total_ms"] > 0
    classes = {k: v for k, v in table.items() if not k.startswith("_")}
    assert classes, "no op classes decoded"
    assert abs(sum(v["share"] for v in classes.values()) - 1.0) < 0.02
    assert all(v["ms"] >= 0 for v in classes.values())


def test_key_averages_without_trace_dir_raises():
    from accelerate_tpu.utils.dataclasses import ProfileKwargs
    from accelerate_tpu.utils.profiler import TPUProfiler

    prof = TPUProfiler(ProfileKwargs())
    with pytest.raises(ValueError, match="output_trace_dir"):
        prof.key_averages()
