"""PLANTED BUGS for the compiled auditor + recompile rules (GL301-GL306).

One function (or source shape) per rule; ``tests/test_preflight.py`` drives
the compiled rules through real AOT ``lower().compile()`` (CPU-safe —
nothing executes) and the AST rules through ``lint_paths``.  Corrected
twins: ``clean_preflight.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np


def donation_dropped_step(state, batch):
    """GL301: the test compiles with ``donate_argnums=(0,)``, but only a
    scalar comes back — XLA's memory analysis shows zero aliased bytes, so
    the donation freed nothing and the caller still lost the buffer."""
    return (state * batch).sum()


def hbm_hog_step(x):
    """GL302 (audited against a deliberately tiny ``--hbm-gb`` budget): the
    64x64 matmul's argument+output+temp footprint blows a 4 KiB budget."""
    return (x @ x.T) + x


# GL303: the declared bucket ladder vs the widths the deploy actually
# compiles — 24 is the stray lowering no bucket predicts (a mid-traffic
# recompile once a 17..24-token prompt arrives)
BUCKETS = (16, 32)
COMPILED_WIDTHS = (16, 24, 32)


def prefill_like(ids):
    """One distinct lowering per input width (the GL303 program set)."""
    return ids.astype(jnp.float32) * 2.0


def promotion_drift_step(state, batch):
    """GL304: the np.float32 learning-rate scalar promotes the donated
    bf16 state to f32 — the fed-back result re-keys the jit cache every
    step, and the widened output can no longer alias the donated buffer."""
    new_state = state - np.float32(0.1) * batch
    return new_state, (state * batch).sum()


@jax.jit
def ragged_positions(ids, start):
    """GL305: ``ids.shape[0]`` flows straight into ``jnp.arange`` and
    ``ids`` is not static — the program re-specializes per prompt length
    (the unbucketed-prefill recompile shape)."""
    return start + jnp.arange(ids.shape[0])


def decode_loop(xs):
    """GL306: a fresh ``jax.jit`` wrapper (and cache) every iteration."""
    out = []
    for x in xs:
        out.append(jax.jit(lambda v: v * 2.0)(x))
    return out


def example_args():
    """Concrete example inputs (tiny; compiling reads only shapes/dtypes)."""
    return {
        "donation_dropped_step": (jnp.ones((64, 64)), jnp.ones((64, 64))),
        "hbm_hog_step": (jnp.ones((64, 64)),),
        "promotion_drift_step": (
            jnp.ones((64, 64), jnp.bfloat16), jnp.ones((64, 64), jnp.bfloat16),
        ),
    }
