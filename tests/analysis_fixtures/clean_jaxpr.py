"""Corrected twins of ``planted_jaxpr.py`` — same shapes, same audit
parameters, zero findings."""

import jax
import jax.numpy as jnp
import numpy as np

_BIG_TABLE = np.ones((600, 600), np.float32)


def wasted_donation_step(state, batch):
    """GL101 fixed: the update has the donated argument's shape/dtype, so
    XLA aliases the donated buffer to it — donation actually frees HBM."""
    new_state = state * 0.9 + batch
    return new_state, (state * batch).sum()


def key_reuse_step(key, x):
    """GL104 fixed: one split child per consumer, parent retired."""
    k_noise, k_mask = jax.random.split(key)
    noise = jax.random.normal(k_noise, x.shape)
    mask = jax.random.uniform(k_mask, x.shape) > 0.1
    return jnp.where(mask, x + noise, x)


def key_reuse_after_split_step(key, x):
    """GL104 fixed: only the split children are consumed."""
    k1, k2 = jax.random.split(key)
    return jax.random.normal(k1, x.shape) + jax.random.normal(k2, x.shape)


def const_capture_step(x, table):
    """GL102 fixed: the table rides in as an argument — shardable,
    donatable, absent from the jaxpr consts."""
    return x @ table


def transfer_in_trace_step(x):
    """GL103 fixed: no placement change inside the trace; the caller owns
    transfers (or routes them through the streaming pipeline stages)."""
    return x * 2.0


def unsharded_output_step(x):
    """GL105 fixed: the producer is a sharding constraint, like the
    accelerator's ``pinned_step_fn``."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    return jax.lax.with_sharding_constraint(x + 1.0, NamedSharding(mesh, PartitionSpec()))


def collective_matmul_hint_step(x, w):
    """GL106 fixed: the gather-then-matmul pipe rides the ring schedule —
    ppermute ticks hidden under partial matmuls, no all_gather in the
    trace (ops/collective_matmul.py)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from accelerate_tpu.ops.collective_matmul import ring_all_gather_matmul

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("x",))

    def body(xl, wl):
        return ring_all_gather_matmul(xl, wl, "x")[0]

    return _shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "x", None), P(None, None)),
        out_specs=P(None, None), **_no_check,
    )(x[None], w)


def collective_matmul_rs_hint_step(x, w):
    """GL107 fixed: the matmul-then-scatter pipe rides the ring schedule —
    per-chunk partial matmuls with ppermute accumulator hops hidden under
    them, no reduce_scatter in the trace (ops/collective_matmul.py)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from accelerate_tpu.ops.collective_matmul import ring_matmul_reduce_scatter

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("x",))

    def body(xl, wl):
        return ring_matmul_reduce_scatter(xl, wl, "x")

    return _shard_map(body, mesh=mesh,
                      in_specs=(P(None, None, "x"), P("x", None)),
                      out_specs=P(None, "x", None), **_no_check)(x, w)


def unscaled_fp8_dot_step(x, w):
    """GL110 fixed: the accumulator is multiplied by the combined inverse
    scale before anything else consumes it — the ops/fp8.py contract
    (fp8_current_scaled_dot is the model)."""
    x_scale = 448.0 / jnp.maximum(jnp.max(jnp.abs(x)), 1e-12)
    w_scale = 448.0 / jnp.maximum(jnp.max(jnp.abs(w)), 1e-12)
    qx = (x * x_scale).astype(jnp.float8_e4m3fn)
    qw = (w * w_scale).astype(jnp.float8_e4m3fn)
    y = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y * (1.0 / (x_scale * w_scale)) + 1.0


def fused_decode_unscaled_kv_step(q, k_codes, v_codes, k_scale, v_scale):
    """GL110 fixed (the fused-decode shape): the in-kernel dequant of
    ``fused_bgmv_paged_decode`` modeled at the jaxpr level — scores carry
    ``k_scale`` and the weighted sum carries ``v_scale`` before anything
    downstream consumes them (the kv_qmax contract)."""
    qk = (q * 448.0).astype(jnp.float8_e4m3fn)
    scores = jax.lax.dot_general(qk, k_codes, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    scores = scores * (k_scale / 448.0)
    out = jax.lax.dot_general(scores, v_codes.astype(jnp.float32),
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return out * v_scale + 1.0


def fused_verify_unscaled_kv_step(q_tokens, k_codes, v_codes, k_scale, v_scale):
    """GL110 fixed (the multi-token verify shape): every contraction over
    the quantized pages is rescaled before the residual add sees it."""
    qk = (q_tokens * 448.0).astype(jnp.float8_e4m3fn)
    scores = jax.lax.dot_general(qk, k_codes, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    scores = scores * (k_scale / 448.0)
    out = jax.lax.dot_general(scores, v_codes.astype(jnp.float32),
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return out * v_scale + q_tokens


def flat_dcn_reduce_step(g):
    """GL108 fixed: the hierarchical decomposition — reduce-scatter inside
    the slice over ICI, all-reduce only the 1/p slab over dcn, all-gather
    back (parallel/hierarchical.py).  The only psum spanning dcn operates
    on the slab, and a dcn-only psum is the hierarchical path's own hop —
    quiet by design."""
    from jax.sharding import Mesh, PartitionSpec as P

    from accelerate_tpu.parallel.hierarchical import hierarchical_sync

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ("dcn", "dp_shard"))

    def body(gl):
        out, _, _ = hierarchical_sync({"g": gl[0]}, ("dp_shard",), "dcn")
        return out["g"]

    from jax.sharding import NamedSharding

    out = _shard_map(body, mesh=mesh, in_specs=P(("dcn", "dp_shard")),
                     out_specs=P(None, None), **_no_check)(g)
    # pin the large output so the fixture stays single-rule (GL105 quiet)
    return jax.lax.with_sharding_constraint(out, NamedSharding(mesh, P(None, None)))


def example_args():
    return {
        "wasted_donation_step": (jnp.ones((64, 64)), jnp.ones((64, 64))),
        "key_reuse_step": (jax.random.key(0), jnp.ones((8,))),
        "key_reuse_after_split_step": (jax.random.key(0), jnp.ones((8,))),
        "const_capture_step": (jnp.ones((600,)), jnp.asarray(_BIG_TABLE)),
        "transfer_in_trace_step": (jnp.ones((8,)),),
        "unsharded_output_step": (jax.ShapeDtypeStruct((1024, 1024), jnp.float32),),
        "collective_matmul_hint_step": (jnp.ones((8, 16)), jnp.ones((16, 4))),
        "collective_matmul_rs_hint_step": (jnp.ones((1, 8, 16)), jnp.ones((16, 4))),
        "unscaled_fp8_dot_step": (jnp.ones((8, 16)), jnp.ones((16, 4))),
        "fused_decode_unscaled_kv_step": (
            jnp.ones((4, 16)), jnp.ones((8, 16), jnp.float8_e4m3fn),
            jnp.ones((8, 16), jnp.float8_e4m3fn), jnp.float32(0.1),
            jnp.float32(0.1),
        ),
        "fused_verify_unscaled_kv_step": (
            jnp.ones((5, 16)), jnp.ones((8, 16), jnp.float8_e4m3fn),
            jnp.ones((8, 16), jnp.float8_e4m3fn), jnp.float32(0.1),
            jnp.float32(0.1),
        ),
        "flat_dcn_reduce_step": (jax.ShapeDtypeStruct((4, 520, 520), jnp.float32),),
    }
