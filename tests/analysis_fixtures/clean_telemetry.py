"""CLEAN twins of ``planted_telemetry.py`` — the same timing shapes with
the hazard corrected (materialize before closing the clock), plus the
quiet shapes GL109 must not fire on.  Every function here must produce
zero findings.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(x):
    return jnp.tanh(x @ x)


jitted_step = jax.jit(lambda x: x * 2.0)


def times_with_block_until_ready(x):
    # the bench.py timed-loop idiom: materialize, then read the clock
    t0 = time.perf_counter()
    y = decorated_step(x)
    jax.block_until_ready(y)
    dt = time.perf_counter() - t0
    return y, dt


def times_with_float_fetch(x):
    t0 = time.perf_counter()
    out = jitted_step(x)
    loss = float(out.sum())
    dt = time.perf_counter() - t0
    return loss, dt


def times_with_host_materialization(x):
    start = time.monotonic()
    y = decorated_step(x)
    arr = np.asarray(y)
    elapsed = time.monotonic() - start
    return arr, elapsed


def times_plain_host_work(rows):
    # no jitted call between the clock reads: plain host timing is quiet
    t0 = time.perf_counter()
    total = sum(len(r) for r in rows)
    dt = time.perf_counter() - t0
    return total, dt


def jitted_call_outside_the_window(x):
    # the jitted call completes BEFORE the timed window opens
    y = decorated_step(x)
    t0 = time.perf_counter()
    total = int(np.asarray(y).sum())
    dt = time.perf_counter() - t0
    return total, dt
