"""CLEAN overload-control twins — the discipline the real engine uses
(``serving/engine.py`` + ``serving/scheduler.py``).

Each function mirrors one in ``planted_overload.py`` with the hazard
retired: the reclaim accounting reads the RETURNED cache (the donated name
is dead after the release dispatch — the production engine's host
``kv_tokens`` mirror plays this role with no device fetch at all), and the
shed arithmetic is host-side with any device mask padded to a static bound
(one compile, ever — the shed path never re-keys compiles).  graft-lint
must stay quiet on every function here.
"""

from functools import partial

import jax
import jax.numpy as jnp


def _release(cache, mask):
    seq_lens = jnp.where(mask, 0, cache["seq_lens"])
    return {"k_pages": cache["k_pages"], "seq_lens": seq_lens}


jitted_release = jax.jit(_release, donate_argnums=(0,))


def cancel_reuses_donated_cache(cache, cancel_mask):
    # the reclaim accounting reads the RETURNED structure: the donated name
    # is dead after the release dispatch (in production the scheduler's
    # host free-page mirror does this arithmetic with no device fetch)
    new_cache = jitted_release(cache, cancel_mask)
    pages_reclaimed = new_cache["seq_lens"].sum()
    return new_cache, pages_reclaimed


@partial(jax.jit, static_argnames=("bound",))
def shed_mask_queue_iota(x, bound):
    """GL305 fixed: the width is a static queue BOUND (``max_queue``), not
    this tick's live queue depth — queues of any length pad up to it, one
    compile ever."""
    return x + jnp.arange(bound)


def example_args():
    cache = {
        "k_pages": jnp.zeros((4, 8, 16), jnp.float32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "cancel_reuses_donated_cache": (cache, jnp.zeros((4,), bool)),
    }
