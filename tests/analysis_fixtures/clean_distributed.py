"""Corrected twins of ``planted_distributed.py`` — the same scenarios with
the distributed contracts honored, so every GL4xx rule stays quiet.

GL401: both roles run the SAME collective schedule.  GL402: the pipeline
re-states the SAME sharding (idempotent pin — no materialized reshard).
GL403: both roles derive identical wire schemas.  GL404: the warmed set
covers everything the schedule can dispatch.
"""

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map as _shard_map

    _no_check = {"check_vma": False}
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    _no_check = {"check_rep": False}

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh():
    return Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("x",))


def gl401_role_a(x):
    """GL401-quiet side A: psum then all_gather."""
    mesh = _mesh()

    def body(xl):
        s = jax.lax.psum(xl, "x")
        return jax.lax.all_gather(s, "x", axis=0, tiled=True)

    return _shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P(None),
                      **_no_check)(x)


def gl401_role_b(x):
    """GL401-quiet side B: the SAME psum-then-all_gather order — every
    rendezvous index pairs identical collectives, so the gang converges."""
    mesh = _mesh()

    def body(xl):
        s = jax.lax.psum(xl, "x")
        return jax.lax.all_gather(s, "x", axis=0, tiled=True)

    return _shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P(None),
                      **_no_check)(x)


def gl401_schedules():
    """Role→schedule map whose sides agree — ``audit_collective_schedules``
    returns no findings."""
    from accelerate_tpu.analysis.distributed_audit import collective_schedule

    x = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    return {
        "role_a": collective_schedule(jax.jit(gl401_role_a).trace(x)),
        "role_b": collective_schedule(jax.jit(gl401_role_b).trace(x)),
    }


def gl402_double_pin_step(x):
    """GL402-quiet: the second constraint re-states the SAME row sharding
    — an idempotent pin materializes nothing, so no reshard is predicted."""
    mesh = _mesh()
    spec = NamedSharding(mesh, P("x", None))
    y = jax.lax.with_sharding_constraint(x * 2.0, spec)
    y = jax.lax.with_sharding_constraint(y, spec)
    return y.sum()


def gl403_schemas():
    """GL403-quiet: both roles derive the schema from the same geometry
    and kv_dtype — ``audit_wire_schema`` finds nothing to flag."""
    from accelerate_tpu.analysis.distributed_audit import wire_schema
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    cfg = LlamaConfig.tiny()
    prefill = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                            num_pages=40, kv_dtype="int8")
    decode = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                           num_pages=40, kv_dtype="int8")
    return wire_schema(cfg, prefill), wire_schema(cfg, decode)


def gl404_coverage():
    """GL404-quiet: the decode role's warmed set covers its full
    dispatchable set — no mid-traffic compile is possible."""
    warmed = {"decode", "release", "wire_recv"}
    return "decode", warmed, {"decode", "release", "wire_recv"}


def example_args():
    """Concrete example inputs for the traceable clean functions."""
    return {
        "gl401_role_a": (jnp.ones((8, 8)),),
        "gl401_role_b": (jnp.ones((8, 8)),),
        "gl402_double_pin_step": (
            jax.ShapeDtypeStruct((1024, 1024), jnp.float32),
        ),
    }
