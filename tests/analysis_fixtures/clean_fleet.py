"""Corrected twin of ``planted_fleet.py``: a well-deployed router pair.

Both roles quantize to int8, so the wire schemas agree (GL403 quiet) and
the handoff wire-leg schedules are symmetric (GL401 quiet) — while the
roles still size their OWN serving geometry (slots, pages, chunk,
buckets, speculation differ freely across the split).  This is the
contract the fleet router relies on: geometry is per-role, the wire
schema is the pair's only shared law.
"""


def router_pair():
    """``(model_config, prefill_plugin, decode_plugin)`` for
    ``pair_preflight`` — audits clean, including the traced wire
    programs (``trace_wire=True``)."""
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    cfg = LlamaConfig.tiny()
    prefill = ServingPlugin(
        num_slots=2, page_size=4, pages_per_slot=8, num_pages=20,
        prefill_chunk=8, prefill_buckets=(4, 8), decode_kernel="native",
        kv_dtype="int8",
    )
    decode = ServingPlugin(
        num_slots=8, page_size=4, pages_per_slot=8, num_pages=64,
        prefill_chunk=4, prefill_buckets=(4,), decode_kernel="native",
        kv_dtype="int8", speculate="ngram", speculate_k=2,
    )
    return cfg, prefill, decode
