"""PLANTED BUGS for the distributed auditor — one scenario per GL4xx rule.

Unlike the GL1xx fixtures these are PAIRS/SETS: each scenario builds the
two role-sides whose *combination* carries the hazard (each side alone is
clean — exactly why the single-program engines can't see it).  The
builders return whatever the matching ``distributed_audit`` entry point
consumes; ``tests/test_analysis.py`` drives them.  Corrected twins:
``clean_distributed.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map as _shard_map

    _no_check = {"check_vma": False}
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    _no_check = {"check_rep": False}

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh():
    return Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("x",))


def gl401_role_a(x):
    """GL401 side A: psum THEN all_gather over axis 'x'."""
    mesh = _mesh()

    def body(xl):
        s = jax.lax.psum(xl, "x")
        return jax.lax.all_gather(s, "x", axis=0, tiled=True)

    return _shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P(None),
                      **_no_check)(x)


def gl401_role_b(x):
    """GL401 side B: all_gather THEN psum — the reversed rendezvous order.
    A gang launched with role A on half the hosts and role B on the other
    half meets a psum opposite an all_gather at rendezvous 0 and deadlocks."""
    mesh = _mesh()

    def body(xl):
        g = jax.lax.all_gather(xl, "x", axis=0, tiled=True)
        return jax.lax.psum(g, "x")

    return _shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P(None),
                      **_no_check)(x)


def gl401_schedules():
    """The role→schedule map ``audit_collective_schedules`` consumes: the
    two sides trace to collective sequences that diverge at index 0."""
    from accelerate_tpu.analysis.distributed_audit import collective_schedule

    x = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    return {
        "role_a": collective_schedule(jax.jit(gl401_role_a).trace(x)),
        "role_b": collective_schedule(jax.jit(gl401_role_b).trace(x)),
    }


def gl402_double_pin_step(x):
    """GL402: a 4 MiB activation pinned to a row sharding and immediately
    re-pinned to a column sharding — GSPMD materializes the un-requested
    reshard (an all-to-all-shaped copy) between the two constraints."""
    mesh = _mesh()
    y = jax.lax.with_sharding_constraint(
        x * 2.0, NamedSharding(mesh, P("x", None))
    )
    y = jax.lax.with_sharding_constraint(y, NamedSharding(mesh, P(None, "x")))
    return y.sum()


def gl403_schemas():
    """GL403: the prefill role quantizes its KV pages to int8 codes+scales
    while the decode role expects dense bf16 — the schemas disagree on
    dtype, payload leaves, and bytes/page.  Returns ``(src, dst)`` for
    ``audit_wire_schema``."""
    from accelerate_tpu.analysis.distributed_audit import wire_schema
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    cfg = LlamaConfig.tiny()
    prefill = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                            num_pages=40, kv_dtype="int8")
    decode = ServingPlugin(num_slots=4, page_size=4, pages_per_slot=16,
                           num_pages=40)
    return wire_schema(cfg, prefill), wire_schema(cfg, decode)


def gl404_coverage():
    """GL404: the decode role warms only the decode program, but the
    schedule can dispatch release and wire_recv to it — the first release
    after warmup compiles mid-traffic (the strict_compiles violation).
    Returns ``(role, warmed, dispatchable)`` for ``audit_warmup_coverage``."""
    return "decode", {"decode"}, {"decode", "release", "wire_recv"}


def example_args():
    """Concrete example inputs for the traceable planted functions."""
    return {
        "gl401_role_a": (jnp.ones((8, 8)),),
        "gl401_role_b": (jnp.ones((8, 8)),),
        "gl402_double_pin_step": (
            jax.ShapeDtypeStruct((1024, 1024), jnp.float32),
        ),
    }
