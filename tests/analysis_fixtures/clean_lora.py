"""CLEAN multi-tenant LoRA twins — the pool discipline the real
AdapterStore uses (``serving/adapters.py``).

Each function mirrors one in ``planted_lora.py`` with the hazard retired:
the insert returns the updated pool (every donated stack aliases an
output in place), and the iota width is a static argument fed from the
fixed pool geometry — one compile regardless of the tenant census.
graft-lint must stay quiet on every function here.
"""

from functools import partial

import jax
import jax.numpy as jnp


def insert_drops_pool(pool, staged, slot):
    """Returns the updated pool: the donated stacks alias the outputs in
    place (the AdapterStore rebinds ``self.pool`` to the result — the
    donated name is dead after the call)."""
    a = pool["a"].at[slot].set(staged["a"])
    b = pool["b"].at[slot].set(staged["b"])
    return {"a": a, "b": b}, jnp.sum(a) + jnp.sum(b)


@partial(jax.jit, static_argnames=("pool_width",))
def adapter_count_iota(x, pool_width):
    """GL305 fixed: the width is the fixed pool geometry passed static —
    the tenant census routes through per-row ids instead of reshaping the
    program."""
    return x + jnp.arange(pool_width)


def example_args():
    pool = {
        "a": jnp.zeros((4, 16, 4), jnp.float32),
        "b": jnp.zeros((4, 4, 16), jnp.float32),
    }
    staged = {
        "a": jnp.ones((16, 4), jnp.float32),
        "b": jnp.ones((4, 16), jnp.float32),
    }
    return {
        "insert_drops_pool": (pool, staged, jnp.asarray(1, jnp.int32)),
    }
