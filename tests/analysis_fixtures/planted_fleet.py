"""PLANTED BUG for the fleet router's go-live gate: a role-mismatched
replica pair behind the router.

The fleet router freely mixes fused engines and disaggregated pairs, and
each pair's two roles may size their OWN geometry (slots, pages, chunk,
buckets, speculation) — but the wire-schema fields (page_size,
pages_per_slot, kv_dtype, prefix convention) are the cross-role contract.
This fixture deploys a prefill role that quantizes KV pages to int8
codes+scales against a decode role expecting dense bf16: routed through
``pair_preflight`` the pair must fire **GL403** (the schemas disagree on
kv_dtype, payload leaves, and bytes/page) AND **GL401** (the handoff
wire-leg schedules diverge — the int8 side streams scale legs the dense
side never receives, so a launched fabric wedges at the first handoff).
Corrected twin: ``clean_fleet.py``.
"""


def router_pair():
    """``(model_config, prefill_plugin, decode_plugin)`` for
    ``pair_preflight`` — the mis-deployed replica the router gate must
    reject before any traffic routes to it."""
    from accelerate_tpu.models import LlamaConfig
    from accelerate_tpu.utils.dataclasses import ServingPlugin

    cfg = LlamaConfig.tiny()
    prefill = ServingPlugin(
        num_slots=2, page_size=4, pages_per_slot=8, num_pages=20,
        prefill_chunk=8, prefill_buckets=(4, 8), decode_kernel="native",
        kv_dtype="int8",  # the planted skew: codes+scales on the wire
    )
    decode = ServingPlugin(
        num_slots=8, page_size=4, pages_per_slot=8, num_pages=64,
        prefill_chunk=4, prefill_buckets=(4,), decode_kernel="native",
    )
    return cfg, prefill, decode
