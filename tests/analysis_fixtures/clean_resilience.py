"""CLEAN GL205 twins — the same checkpoint writes done durably.

Each function mirrors one in ``planted_resilience.py`` with the hazard
retired: files stage under ``<dir>.tmp`` and one ``os.replace`` publishes
(the ``checkpointing._finalize_checkpoint`` idiom), and failures on the
restore spine are logged and re-raised instead of swallowed.  The rule must
stay quiet on every function here.
"""

import json
import logging
import os
import pickle

logger = logging.getLogger(__name__)


def save_weights_atomic(step, payload):
    # stage in .tmp, publish with one atomic rename
    tmp = f"checkpoints/checkpoint_{step}.tmp"
    final = tmp[: -len(".tmp")]
    os.makedirs(tmp, exist_ok=True)
    with open(f"{tmp}/weights.bin", "wb") as f:
        f.write(payload)
    os.replace(tmp, final)
    return final


def save_meta_atomic(step, meta):
    tmp = f"checkpoints/checkpoint_{step}.tmp"
    with open(f"{tmp}/meta.json", "w") as f:
        json.dump(meta, f)
    os.replace(tmp, tmp[: -len(".tmp")])


def save_rng_atomic(step, rng_state):
    tmp = f"checkpoints/checkpoint_{step}.tmp"
    with open(f"{tmp}/rng.pkl", "wb") as f:
        pickle.dump(rng_state, f)
    os.replace(tmp, tmp[: -len(".tmp")])


def restore_surfacing_failures(path):
    # failures surface: logged with context, then re-raised for the
    # fallback scan to handle
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception as e:
        logger.warning("restore of %s failed: %s", path, e)
        raise
