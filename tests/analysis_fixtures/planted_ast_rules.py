"""PLANTED BUGS — one per AST rule (GL202/GL203/GL204).

Linted as source only, never imported.  Each planted call sits inside a
function the engine must recognize as a jit context (decorated, passed to
``jax.jit``, or reached transitively from one).  Corrected twins:
``clean_ast_rules.py``.
"""

import random
import time

import jax
import numpy as np


@jax.jit
def step_with_host_syncs(x):
    loss = (x * x).sum()
    scalar = loss.item()          # GL202: device->host sync under trace
    host = np.asarray(x)          # GL202: materializes the tracer
    lr = float(x)                 # GL202: concretizes a traced argument
    return loss + scalar + host.sum() + lr


def _inner_metrics(x):
    # reached from step_with_impurity below — jit context by propagation
    return x.tolist()             # GL202: sync in transitively-jitted code


def step_with_impurity(x, seed):
    stamp = time.time()           # GL204: baked in at trace time
    jitter = random.random()      # GL204: host randomness drawn once
    noise = np.random.rand()      # GL204: numpy RNG under trace
    return x * stamp + jitter + noise + sum(_inner_metrics(x))


jitted_impure = jax.jit(step_with_impurity, static_argnums=(1,))

from jax.experimental.shard_map import shard_map  # noqa: E402,F401  GL203: no compat fallback
