"""CLEAN serving-decode twins — the donation-clean step shape the real
engine uses (``serving/engine.py``).

Each function mirrors one in ``planted_serving.py`` with the hazard
retired: post-step reads go through the RETURNED cache (the donated name is
dead after the call), and the step returns the updated pool so the donated
buffers alias outputs in place.  graft-lint must stay quiet on every
function here.
"""

import jax
import jax.numpy as jnp


def _decode(cache, token):
    k_pages = cache["k_pages"].at[0, 0].set(token)
    logits = jnp.sum(k_pages, axis=(0, 1))
    return {"k_pages": k_pages, "seq_lens": cache["seq_lens"] + 1}, logits


jitted_decode = jax.jit(_decode, donate_argnums=(0,))


def serve_step_reuses_donated_cache(cache, token):
    # the returned structure is the only live view of the pool
    new_cache, logits = jitted_decode(cache, token)
    used_pages = new_cache["seq_lens"].sum()
    return new_cache, logits, used_pages


def decode_step_drops_pool(cache, token):
    """Returns the updated pool alongside the logits: every donated buffer
    aliases an output of the same byte size — the donation is consumed."""
    k_pages = cache["k_pages"].at[0, 0].set(token)
    return {"k_pages": k_pages, "seq_lens": cache["seq_lens"]}, jnp.sum(k_pages, axis=(0, 1))


def example_args():
    cache = {
        "k_pages": jnp.zeros((4, 8, 16), jnp.float32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "serve_step_reuses_donated_cache": (cache, jnp.ones((16,), jnp.float32)),
        "decode_step_drops_pool": (cache, jnp.ones((16,), jnp.float32)),
    }
