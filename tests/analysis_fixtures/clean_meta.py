"""Corrected twins of ``planted_meta.py`` / ``planted_engine_error.py``.

GL001-quiet: the suppression marker carries its rationale, so the GL204 it
silences is documented.  GL002-quiet: the module parses — the engine has
nothing to report about its own run.
"""

import time

import jax


@jax.jit
def step_with_documented_marker(x):
    return x * time.time()  # graft-lint: disable=GL204 -- fixture: wall-clock scaling is this twin's point
