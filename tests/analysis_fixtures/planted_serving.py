"""PLANTED serving-decode fixtures — the donation hazards the paged-KV
serving step must never ship with.

The serving engine's decode step donates the whole cache pytree (pool
arrays update in place); these functions carry the two ways that contract
breaks: reading the donated pool after the step (GL201 — the async-ckpt
race shape applied to serving) and a step whose outputs cannot alias the
donated pool (GL101, wasted donation).  Corrected twins:
``clean_serving.py``.  Excluded from repo-wide sweeps like the rest of this
directory.
"""

import jax
import jax.numpy as jnp


def _decode(cache, token):
    k_pages = cache["k_pages"].at[0, 0].set(token)
    logits = jnp.sum(k_pages, axis=(0, 1))
    return {"k_pages": k_pages, "seq_lens": cache["seq_lens"] + 1}, logits


jitted_decode = jax.jit(_decode, donate_argnums=(0,))


def serve_step_reuses_donated_cache(cache, token):
    # GL201: `cache`'s pool buffers were donated to the step — XLA may
    # already be overwriting them in place when this utilization probe reads
    # seq_lens off the STALE structure instead of the returned one
    new_cache, logits = jitted_decode(cache, token)
    used_pages = cache["seq_lens"].sum()
    return new_cache, logits, used_pages


def decode_step_drops_pool(cache, token):
    """GL101 (the test jits with donate_argnums=(0,)): only the logits come
    back — no output can alias the donated page pool, so the donation frees
    nothing and the caller still loses the cache."""
    k_pages = cache["k_pages"].at[0, 0].set(token)
    return jnp.sum(k_pages, axis=(0, 1))


def example_args():
    cache = {
        "k_pages": jnp.zeros((4, 8, 16), jnp.float32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "serve_step_reuses_donated_cache": (cache, jnp.ones((16,), jnp.float32)),
        "decode_step_drops_pool": (cache, jnp.ones((16,), jnp.float32)),
    }
