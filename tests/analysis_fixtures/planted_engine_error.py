# PLANTED GL002: this file is deliberately NOT valid Python — the AST
# engine must report its own failure to parse a target loudly (GL002)
# rather than silently skipping the file.  Clean twin: clean_meta.py
# (a parseable module).  Never import this module.
def broken(:
    return
