"""CLEAN speculative-decode twins — the discipline the real engine uses
(``serving/engine.py`` + ``serving/speculate.py``).

Each function mirrors one in ``planted_speculate.py`` with the hazard
retired: the drafting layer sizes the next proposals off the RETURNED
cache (the donated name is dead after the verify call — the engine keeps
its own host-side ``kv_len`` mirror and never touches the donated pytree),
and the verify width is a static bucket from the fixed
``speculate_buckets`` ladder — one compile per bucket, never per draft
depth.  graft-lint must stay quiet on every function here.
"""

from functools import partial

import jax
import jax.numpy as jnp


def _verify(cache, tokens):
    k_pages = cache["k_pages"].at[0, 0].set(tokens[0])
    greedy = jnp.argmax(jnp.sum(k_pages, axis=(0, 1)), axis=-1)
    return {"k_pages": k_pages, "seq_lens": cache["seq_lens"] + 1}, greedy


jitted_verify = jax.jit(_verify, donate_argnums=(0,))


def draft_reuses_donated_cache(cache, tokens):
    # the draft context reads the RETURNED cache: the donated name is dead
    # after the verify dispatch (the engine's host kv_len mirror plays this
    # role in production — no device fetch at all)
    new_cache, greedy = jitted_verify(cache, tokens)
    draft_context_len = new_cache["seq_lens"] + 1
    return new_cache, greedy, draft_context_len


@partial(jax.jit, static_argnames=("bucket",))
def verify_width_iota(x, bucket):
    """GL305 fixed: the width is a bucket from the fixed speculate ladder
    passed static — draft depths pad up to it, one compile per bucket."""
    return x + jnp.arange(bucket)


def example_args():
    cache = {
        "k_pages": jnp.zeros((4, 8, 16), jnp.float32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "draft_reuses_donated_cache": (cache, jnp.ones((16,), jnp.float32)),
    }
