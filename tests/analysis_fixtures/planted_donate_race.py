"""PLANTED BUG — the PR 2 async-checkpoint use-after-donate race, minimal.

``save_state(async_save=True)`` used to hand the live train state to the
background orbax writer *after* the prepared step had donated its buffers:
on the CPU backend the write aliases the arrays zero-copy, so checkpoint N
could restore with checkpoint N+1's values.  This module reproduces the
exact caller shape the AST engine must flag (GL201): a name passed in the
donated position of a ``donate_argnums`` call site, then read again by the
background-writer handoff.

Never imported by the suite — linted as source only.  The corrected twin
lives in ``fixed_donate_race.py``.
"""

import threading

import jax


def _write_to_disk(tree, path="/tmp/ckpt"):
    """Stand-in for the orbax background writer: reads ``tree``'s buffers
    asynchronously, long after this function returned."""
    _ = (tree, path)


def _train_step(state, batch):
    return {"params": state["params"] * 0.9 + batch.mean()}


jitted_step = jax.jit(_train_step, donate_argnums=(0,))


def train_then_snapshot(state, batch):
    new_state = jitted_step(state, batch)
    # BUG: `state`'s buffers were donated to the step above — the writer
    # thread reads them while XLA may already be overwriting them in place.
    writer = threading.Thread(target=_write_to_disk, args=(state,))
    writer.start()
    return new_state
