"""PLANTED BUGS for the jaxpr auditor — one function per GL1xx rule.

These ARE imported and traced (abstractly — ``jax.jit(...).trace``, no
device execution) by ``tests/test_analysis.py``; each function carries the
hazard in its traced program, invisible to a source-level linter.
Corrected twins: ``clean_jaxpr.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np

# ~1.4 MiB closed-over constant (above the 1 MiB default threshold)
_BIG_TABLE = np.ones((600, 600), np.float32)


def wasted_donation_step(state, batch):
    """GL101: ``state`` is donated (the test jits with donate_argnums=(0,))
    but the function returns only a scalar — no output can alias the
    donated (64, 64) buffer, so the donation frees nothing."""
    return (state * batch).sum()


def key_reuse_step(key, x):
    """GL104: the same key feeds two random primitives — the 'noise' and
    'dropout' streams are identical."""
    noise = jax.random.normal(key, x.shape)
    mask = jax.random.uniform(key, x.shape) > 0.1
    return jnp.where(mask, x + noise, x)


def key_reuse_after_split_step(key, x):
    """GL104 (the classic): the parent key is split AND consumed directly —
    the direct stream correlates with the children."""
    k1, _k2 = jax.random.split(key)
    direct = jax.random.normal(key, x.shape)  # parent already retired by split
    child = jax.random.normal(k1, x.shape)
    return direct + child


def const_capture_step(x):
    """GL102: ``_BIG_TABLE`` closes over into the jaxpr as a constant —
    re-uploaded per executable, invisible to donation and sharding."""
    return x @ _BIG_TABLE


def transfer_in_trace_step(x):
    """GL103 (audited with ``default_memory_kind='device'``): an explicit
    device_put inside traced code — on TPU this is a host<->device copy
    serialized into the step."""
    y = x * 2.0
    dst = jax.sharding.SingleDeviceSharding(
        jax.devices()[0], memory_kind=jax.devices()[0].default_memory().kind
    )
    return jax.device_put(y, dst)


def unsharded_output_step(x):
    """GL105: a 4 MiB output whose producer is a plain add — GSPMD may
    resolve it fully replicated."""
    return x + 1.0  # x: (1024, 1024) f32


def collective_matmul_hint_step(x, w):
    """GL106 (hint): the gathered activations feed exactly ONE dot_general —
    the monolithic all-gather→matmul pipe a ring collective-matmul would
    hide inside the partial matmuls.  Only the trace sees the fan-out."""
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("x",))

    def body(xl, wl):
        full = jax.lax.all_gather(xl, "x", axis=0, tiled=True)
        return jax.lax.dot_general(full, wl, (((1,), (0,)), ((), ())))

    return _shard_map(body, mesh=mesh, in_specs=(P("x", None), P(None, None)),
                      out_specs=P(None, None), **_no_check)(x, w)


def collective_matmul_rs_hint_step(x, w):
    """GL107 (hint): the row-parallel mirror of GL106 — the full partial
    matmul finishes before ONE monolithic reduce_scatter starts.  Only the
    trace sees the single-consumer pipe."""
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("x",))

    def body(xl, wl):
        part = jax.lax.dot_general(xl, wl, (((2,), (0,)), ((), ())))
        return jax.lax.psum_scatter(part, "x", scatter_dimension=1, tiled=True)

    return _shard_map(body, mesh=mesh,
                      in_specs=(P(None, None, "x"), P("x", None)),
                      out_specs=P(None, "x", None), **_no_check)(x, w)


def unscaled_fp8_dot_step(x, w):
    """GL110: both operands cast to fp8 codes, matmul'd, and the
    accumulator consumed by an add with NO dequantizing mul/div — the
    downstream math runs on values off by the combined scale factor (the
    loss still goes down, just slower, which is why only the trace catches
    it)."""
    qx = (x * 448.0).astype(jnp.float8_e4m3fn)
    qw = (w * 448.0).astype(jnp.float8_e4m3fn)
    y = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y + 1.0  # raw fp8 codes flow into the add


def fused_decode_unscaled_kv_step(q, k_codes, v_codes, k_scale, v_scale):
    """GL110 (the fused-decode shape of PR 17): the jaxpr model of
    ``fused_bgmv_paged_decode``'s quantized-KV contraction — scores off an
    fp8 K-page dot and the weighted sum over fp8 V-pages reach the output
    add with NEITHER ``k_scale`` nor ``v_scale`` applied.  The fused kernel
    dequantizes in-kernel (``kv_qmax`` scaling); this model drops it."""
    qk = (q * 448.0).astype(jnp.float8_e4m3fn)
    scores = jax.lax.dot_general(qk, k_codes, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    qs = (scores * 448.0).astype(jnp.float8_e4m3fn)
    out = jax.lax.dot_general(qs, v_codes, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    del k_scale, v_scale  # the planted bug: scales never touch the chain
    return out + 1.0


def fused_verify_unscaled_kv_step(q_tokens, k_codes, v_codes, k_scale, v_scale):
    """GL110 (the multi-token verify shape of PR 17): the verify window's
    k+1 queries attend over the same quantized pages — one dot per
    contraction, still no dequantizing mul before the residual add."""
    qk = (q_tokens * 448.0).astype(jnp.float8_e4m3fn)
    scores = jax.lax.dot_general(qk, k_codes, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    qs = (scores * 448.0).astype(jnp.float8_e4m3fn)
    out = jax.lax.dot_general(qs, v_codes, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    del k_scale, v_scale
    return out + q_tokens  # raw codes land in the residual stream


def flat_dcn_reduce_step(g):
    """GL108 (hint): a >= 1 MiB gradient psum over the JOINT ('dcn',
    'dp_shard') axes — the flat reduction whose cross-slice leg moves one
    full-size copy per intra-slice device over the slow DCN link.  The
    hierarchical decomposition (clean twin) reduce-scatters over ICI first
    so only the 1/p slab crosses dcn."""
    from jax.sharding import Mesh, PartitionSpec as P

    try:
        from jax import shard_map as _shard_map

        _no_check = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        _no_check = {"check_rep": False}

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ("dcn", "dp_shard"))

    def body(gl):
        return jax.lax.psum(gl[0], ("dcn", "dp_shard"))

    return _shard_map(body, mesh=mesh, in_specs=P(("dcn", "dp_shard")),
                      out_specs=P(None, None), **_no_check)(g)


def example_args():
    """Concrete example inputs for each planted function (tiny; tracing
    only reads shapes/dtypes)."""
    return {
        "wasted_donation_step": (jnp.ones((64, 64)), jnp.ones((64, 64))),
        "key_reuse_step": (jax.random.key(0), jnp.ones((8,))),
        "key_reuse_after_split_step": (jax.random.key(0), jnp.ones((8,))),
        "const_capture_step": (jnp.ones((600,)),),
        "transfer_in_trace_step": (jnp.ones((8,)),),
        "unsharded_output_step": (jax.ShapeDtypeStruct((1024, 1024), jnp.float32),),
        "collective_matmul_hint_step": (jnp.ones((8, 16)), jnp.ones((16, 4))),
        "collective_matmul_rs_hint_step": (jnp.ones((1, 8, 16)), jnp.ones((16, 4))),
        "unscaled_fp8_dot_step": (jnp.ones((8, 16)), jnp.ones((16, 4))),
        # q [H, D] / q_tokens [T, D] against P quantized pages of width D
        "fused_decode_unscaled_kv_step": (
            jnp.ones((4, 16)), jnp.ones((8, 16), jnp.float8_e4m3fn),
            jnp.ones((8, 16), jnp.float8_e4m3fn), jnp.float32(0.1),
            jnp.float32(0.1),
        ),
        "fused_verify_unscaled_kv_step": (
            jnp.ones((5, 16)), jnp.ones((8, 16), jnp.float8_e4m3fn),
            jnp.ones((8, 16), jnp.float8_e4m3fn), jnp.float32(0.1),
            jnp.float32(0.1),
        ),
        # per-device operand after the leading world-axis index: 520*520*4
        # ≈ 1.03 MiB — above the 1 MiB GL108 threshold
        "flat_dcn_reduce_step": (jax.ShapeDtypeStruct((4, 520, 520), jnp.float32),),
    }
