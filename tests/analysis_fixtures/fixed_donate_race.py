"""Corrected twin of ``planted_donate_race.py`` — the PR 2 fix shape.

The snapshot (a sharding-preserving jit identity copy, exactly what
``checkpointing._sharded_copy_fn`` does) is taken BEFORE the donating call,
so the background writer reads buffers the step never owned.  The donated
name is dead after the call site: GL201 must stay quiet here.
"""

import threading

import jax
import jax.numpy as jnp


def _write_to_disk(tree, path="/tmp/ckpt"):
    _ = (tree, path)


def _train_step(state, batch):
    return {"params": state["params"] * 0.9 + batch.mean()}


jitted_step = jax.jit(_train_step, donate_argnums=(0,))

_identity_copy = jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))


def train_then_snapshot(state, batch):
    snapshot = _identity_copy(state)  # synchronous-snapshot half of the contract
    new_state = jitted_step(state, batch)
    writer = threading.Thread(target=_write_to_disk, args=(snapshot,))
    writer.start()
    return new_state
