"""PLANTED telemetry-timing fixtures — clock deltas that measure async
dispatch, not compute (GL109, INFO hint).

jax dispatch is asynchronous: a ``perf_counter()`` delta closed before the
jitted call's outputs materialize times the host-side enqueue, and the
"speedup" it reports is an artifact.  Corrected twins:
``clean_telemetry.py``.  Excluded from repo-wide sweeps like the rest of
this directory.
"""

import time

import jax
import jax.numpy as jnp


@jax.jit
def decorated_step(x):
    return jnp.tanh(x @ x)


jitted_step = jax.jit(lambda x: x * 2.0)


def times_async_dispatch(x):
    # GL109: the delta closes with no materialization after the jitted call
    t0 = time.perf_counter()
    y = decorated_step(x)
    dt = time.perf_counter() - t0
    return y, dt


def times_bound_jit_wrapper(x):
    # GL109 through a `name = jax.jit(...)` binding (not a decorator)
    start = time.monotonic()
    out = jitted_step(x)
    elapsed = time.monotonic() - start
    return out, elapsed


def times_inline_jit_call(x):
    # GL109 with the jit wrapper constructed and called inline
    t0 = time.perf_counter()
    y = jax.jit(lambda v: v + 1)(x)
    dt = time.perf_counter() - t0
    return y, dt


def materializes_before_the_last_dispatch(x):
    # GL109: the float() sync covers the FIRST call only — the second
    # jitted call is still in flight when the clock closes
    t0 = time.perf_counter()
    y = decorated_step(x)
    float(y.sum())
    z = decorated_step(y)
    dt = time.perf_counter() - t0
    return z, dt
