"""PLANTED META-RULE VIOLATIONS — the engine-discipline rules themselves.

GL001: a bare suppression marker (no ``-- rationale``) that DOES silence a
real finding — the suppression works, but the missing rationale is itself
reported.  GL002's planted twin is ``planted_engine_error.py`` (a file the
AST engine cannot parse — referenced here because this module must stay
importable).  Corrected twins: ``clean_meta.py``.
"""

import time

import jax


@jax.jit
def step_with_bare_marker(x):
    # the marker below suppresses the GL204 wall-clock read but omits its
    # rationale -- the GL001 shape
    return x * time.time()  # graft-lint: disable=GL204
