"""Corrected twins of ``planted_preflight.py`` — same audit parameters,
zero findings."""

from functools import partial

import jax
import jax.numpy as jnp


def donation_dropped_step(state, batch):
    """GL301 fixed: the update has the donated argument's exact aval, so
    the compiled executable aliases the donated buffer to it."""
    return state * 0.9 + batch, (state * batch).sum()


def hbm_hog_step(x):
    """GL302 fixed (same 4 KiB budget): the footprint shrank to fit it —
    the example input is a vector, not the 64x64 working set."""
    return x * 2.0 + 1.0


# GL303 fixed: every compiled width IS a declared bucket
BUCKETS = (16, 32)
COMPILED_WIDTHS = (16, 32)


def prefill_like(ids):
    return ids.astype(jnp.float32) * 2.0


def promotion_drift_step(state, batch):
    """GL304 fixed: the scalar is typed to the state's dtype, so the
    output aval equals the donated input aval — stable cache key, live
    donation alias."""
    new_state = state - jnp.asarray(0.1, state.dtype) * batch
    return new_state, (state * batch).sum()


@partial(jax.jit, static_argnames=("width",))
def ragged_positions(ids, start, width):
    """GL305 fixed: the width is an explicit static argument fed from the
    bucket ladder — no traced-shape read, one compile per declared bucket."""
    del ids
    return start + jnp.arange(width)


@partial(jax.jit, static_argnums=(0,))
def bucketed_zeros(spec, x):
    """GL305's static exemption: reading ``.shape`` of a STATIC argument is
    trace-time constant folding, not shape drift — stays quiet."""
    return jnp.zeros(spec.shape[0]) + x.sum()


_jitted_decode = jax.jit(lambda v: v * 2.0)


def decode_loop(xs):
    """GL306 fixed: one wrapper hoisted above the loop; jit caches the
    compiled program across iterations."""
    return [_jitted_decode(x) for x in xs]


def step_factories(scales):
    """GL306's defined-not-executed exemption: the jit lives in a function
    *defined* in the loop body — each wrapper is constructed once, when the
    factory is later called, not per loop iteration.  Stays quiet."""
    factories = []
    for scale in scales:
        def make(s=scale):
            return jax.jit(lambda v: v * s)
        factories.append(make)
    return factories


def example_args():
    return {
        "donation_dropped_step": (jnp.ones((64, 64)), jnp.ones((64, 64))),
        "hbm_hog_step": (jnp.ones((8,)),),
        "promotion_drift_step": (
            jnp.ones((64, 64), jnp.bfloat16), jnp.ones((64, 64), jnp.bfloat16),
        ),
    }
