"""PLANTED overload-control hazards — the two ways the cancellation/shed
machinery breaks the serving contracts (corrected twins:
``clean_overload.py``).

Cancellation releases a request's pages through the donated release
program; the tempting bug is computing the ``pages_reclaimed_on_cancel``
accounting off the DONATED cache structure after the release dispatch —
``cancel_reuses_donated_cache`` carries that shape (GL201, the async-ckpt
race applied across the cancel/release boundary; the real engine keeps a
host-side mirror and never touches the donated pytree).
``shed_mask_queue_iota`` carries the queue-length-dependent trace (GL305):
a shed program keyed on the waiting line's length re-specializes every time
the queue grows or shrinks — the shed path must never re-key compiles
(admission control is HOST arithmetic; anything on device pads to a fixed
bound).  Excluded from repo-wide sweeps like the rest of this directory.
"""

import jax
import jax.numpy as jnp


def _release(cache, mask):
    seq_lens = jnp.where(mask, 0, cache["seq_lens"])
    return {"k_pages": cache["k_pages"], "seq_lens": seq_lens}


jitted_release = jax.jit(_release, donate_argnums=(0,))


def cancel_reuses_donated_cache(cache, cancel_mask):
    # GL201: `cache` was donated to the release step — XLA may already be
    # overwriting its buffers in place when the reclaim accounting reads
    # seq_lens off the STALE structure instead of the returned one (the
    # production engine reads its host kv_tokens mirror: no device fetch)
    new_cache = jitted_release(cache, cancel_mask)
    pages_reclaimed = cache["seq_lens"].sum()
    return new_cache, pages_reclaimed


@jax.jit
def shed_mask_queue_iota(queued_deadlines, x):
    """GL305: ``queued_deadlines.shape[0]`` (this tick's waiting-line
    length) flows straight into ``jnp.arange`` and the queue is not static
    — the shed program re-specializes per queue depth instead of padding to
    a fixed bound (the mid-traffic recompile ``strict_compiles`` exists to
    catch; shedding must not re-key compiles)."""
    return x + jnp.arange(queued_deadlines.shape[0])


def example_args():
    cache = {
        "k_pages": jnp.zeros((4, 8, 16), jnp.float32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "cancel_reuses_donated_cache": (cache, jnp.zeros((4,), bool)),
    }
