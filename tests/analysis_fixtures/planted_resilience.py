"""PLANTED GL205 fixtures — intentionally torn-write-prone checkpoint code.

Every function here contains a checkpoint-durability hazard the
non-atomic-checkpoint rule must flag (the corrected twin is
``clean_resilience.py``).  Excluded from repo-wide sweeps like the rest of
this directory.
"""

import json
import os
import pickle


def save_weights_non_atomic(step, payload):
    # GL205(a): writes straight into the live checkpoint dir — a crash
    # mid-write leaves a directory that looks like a checkpoint
    d = f"checkpoints/checkpoint_{step}"
    os.makedirs(d, exist_ok=True)
    with open(f"{d}/weights.bin", "wb") as f:
        f.write(payload)
    return d


def save_meta_non_atomic(step, meta):
    # GL205(a): json.dump into a live checkpoint path, no os.replace in scope
    with open(f"checkpoints/checkpoint_{step}/meta.json", "w") as f:
        json.dump(meta, f)


def save_rng_non_atomic(step, rng_state):
    # GL205(a): pickle.dump variant
    with open(f"checkpoints/checkpoint_{step}/rng.pkl", "wb") as f:
        pickle.dump(rng_state, f)


def restore_swallowing_failures(path):
    # GL205(b): a swallowed restore failure reads as success — the caller
    # trains on from garbage
    state = {}
    try:
        with open(path, "rb") as f:
            state = pickle.load(f)
    except Exception:
        pass
    return state
