"""PLANTED BUG — donate-under-pending-snapshot (GL206), minimal.

``save_state(async_save=True)`` returns as soon as the background orbax
writer is armed; the writer then reads the handed-in train state's buffers
off the step critical path.  Donating that SAME name to the compiled step
before the write drains re-opens the aliasing race the sharding-preserving
copy in ``save_accelerator_state`` exists to close: checkpoint N can land
with step N+1's values.  This module reproduces the exact caller shape the
AST engine must flag (GL206): the name goes to an ``async_save=True``
initiator, then into a donated position, with no rebind or drain between.

Never imported by the suite — linted as source only.  The corrected twin
lives in ``clean_snapshot_race.py``.
"""

import jax


def _train_step(state, batch):
    return {"params": state["params"] * 0.9 + batch.mean()}


jitted_step = jax.jit(_train_step, donate_argnums=(0,))


def snapshot_then_train(acc, state, batch):
    acc.save_state(train_state=state, async_save=True)
    # BUG: the background writer may still be reading `state`'s buffers
    # while the donated step overwrites them in place.
    new_state = jitted_step(state, batch)
    return new_state
