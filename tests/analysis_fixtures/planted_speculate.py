"""PLANTED speculative-decode hazards — the two ways the draft-and-verify
contract breaks (corrected twins: ``clean_speculate.py``).

The serving engine's verify step donates the whole cache pytree (allocate +
multi-token append + page rollback all alias in place); the drafting layer
runs on the host BETWEEN verify passes, so the tempting bug is reading the
donated structure for the next draft's context while XLA may already be
overwriting it — ``draft_reuses_donated_cache`` carries that shape (GL201,
the async-ckpt race applied across the draft/verify boundary).
``verify_width_iota`` carries the k-dependent trace (GL305): a verify
program keyed on the drafts' width recompiles whenever a request's draft
depth changes — exactly what the fixed ``speculate_buckets`` ladder exists
to prevent.  Excluded from repo-wide sweeps like the rest of this
directory.
"""

import jax
import jax.numpy as jnp


def _verify(cache, tokens):
    k_pages = cache["k_pages"].at[0, 0].set(tokens[0])
    greedy = jnp.argmax(jnp.sum(k_pages, axis=(0, 1)), axis=-1)
    return {"k_pages": k_pages, "seq_lens": cache["seq_lens"] + 1}, greedy


jitted_verify = jax.jit(_verify, donate_argnums=(0,))


def draft_reuses_donated_cache(cache, tokens):
    # GL201: `cache` was donated to the verify step — XLA may already be
    # scribbling over its pool buffers when the drafting layer reads
    # seq_lens off the STALE structure to size the next proposals, instead
    # of the returned cache
    new_cache, greedy = jitted_verify(cache, tokens)
    draft_context_len = cache["seq_lens"] + 1
    return new_cache, greedy, draft_context_len


@jax.jit
def verify_width_iota(drafts, x):
    """GL305: ``drafts.shape[1]`` (this pass's draft depth) flows straight
    into ``jnp.arange`` and the drafts are not static — the verify program
    re-specializes per k instead of padding to a ``speculate_buckets``
    width (the mid-traffic recompile ``strict_compiles`` exists to catch)."""
    return x + jnp.arange(drafts.shape[1])


def example_args():
    cache = {
        "k_pages": jnp.zeros((4, 8, 16), jnp.float32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "draft_reuses_donated_cache": (cache, jnp.ones((16,), jnp.float32)),
    }
