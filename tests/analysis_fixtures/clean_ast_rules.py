"""Corrected twins of ``planted_ast_rules.py`` — graft-lint must stay
quiet on every one of these (GL202 host syncs, GL203 shard_map import,
GL204 impure calls under trace)."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def step_without_host_syncs(x):
    # metrics stay abstract; the caller reads them outside the jit
    loss = (x * x).sum()
    return loss, jnp.mean(x)


def read_metrics_outside(step_out):
    # host sync is fine here: nothing in this function runs under trace
    loss, mean = step_out
    return float(loss), mean.item()


def step_with_threaded_inputs(x, stamp, key):
    # wall-clock and randomness ride in as arguments
    noise = jax.random.normal(key, x.shape)
    return x * stamp + noise


jitted_pure = jax.jit(step_with_threaded_inputs)


def make_inputs(x):
    # impurity lives outside the trace, threaded in per call
    return x, time.time(), jax.random.key(0)


try:
    from jax import shard_map  # noqa: F401
except ImportError:  # older jax — the sanctioned compat fallback shape
    from jax.experimental.shard_map import shard_map  # noqa: F401
