"""Corrected twin of ``planted_snapshot_race.py`` — both safe shapes.

Shape 1 drains the pending write (``wait_for_checkpoint``) before donating,
so the background reader is finished by the time XLA reuses the buffers.
Shape 2 rebinds the name from the donating call's result before the next
initiator sees it, so each async write only ever holds buffers no later
step donates.  GL206 must stay quiet on both.
"""

import jax


def _train_step(state, batch):
    return {"params": state["params"] * 0.9 + batch.mean()}


jitted_step = jax.jit(_train_step, donate_argnums=(0,))


def drain_then_train(acc, state, batch):
    acc.save_state(train_state=state, async_save=True)
    acc.wait_for_checkpoint()  # background read fenced before donation
    new_state = jitted_step(state, batch)
    return new_state


def train_then_snapshot_next(acc, state, batch):
    new_state = jitted_step(state, batch)
    acc.save_state(train_state=new_state, async_save=True)
    # `state` was donated BEFORE the initiator armed, and the initiator
    # holds `new_state`, which is never donated here.
    return new_state
