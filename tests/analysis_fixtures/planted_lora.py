"""PLANTED multi-tenant LoRA hazards — the two ways the adapter-pool
contract breaks (corrected twins: ``clean_lora.py``).

The serving AdapterStore's hot-swap insert donates the device pool (the
stacks alias in place, like the paged-KV cache); ``insert_drops_pool``
carries the dropped-donation shape (GL101 — the test jits it with
``donate_argnums=(0,)``).  ``adapter_count_iota`` carries the
adapter-count-dependent trace (GL305): a program keyed on ``len(pool)``
recompiles every time the tenant census changes — exactly what the
fixed-width pool + id routing exist to prevent.  Excluded from repo-wide
sweeps like the rest of this directory.
"""

import jax
import jax.numpy as jnp


def insert_drops_pool(pool, staged, slot):
    """GL101 (jitted with ``donate_argnums=(0,)`` by the test): the updated
    a/b stacks never come back — no output can alias the donated pool, so
    the donation frees nothing and the caller loses the resident adapters."""
    a = pool["a"].at[slot].set(staged["a"])
    b = pool["b"].at[slot].set(staged["b"])
    return jnp.sum(a) + jnp.sum(b)


@jax.jit
def adapter_count_iota(a_stack, x):
    """GL305: ``a_stack.shape[0]`` flows straight into ``jnp.arange`` and
    the stack is not static — the program re-specializes per resident
    adapter count (the per-tenant-mix recompile the segment-batched pool
    removes)."""
    return x + jnp.arange(a_stack.shape[0])


def example_args():
    pool = {
        "a": jnp.zeros((4, 16, 4), jnp.float32),
        "b": jnp.zeros((4, 4, 16), jnp.float32),
    }
    staged = {
        "a": jnp.ones((16, 4), jnp.float32),
        "b": jnp.ones((4, 16), jnp.float32),
    }
    return {
        "insert_drops_pool": (pool, staged, jnp.asarray(1, jnp.int32)),
    }
