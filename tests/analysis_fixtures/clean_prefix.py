"""CLEAN prefix-cache twins — the discipline the real engine uses
(``serving/engine.py`` ``_prefix_fns`` + ``serving/prefix_cache.py``).

Each function mirrors one in ``planted_prefix.py`` with the hazard
retired: the keep-count accounting reads the RETURNED cache (the donated
name is dead after the adopt dispatch — in production the host
``SlotState.shared_pages`` mirror plays this role with no device fetch at
all), and the adopt arithmetic pads the shared-page id vector to the
static ``pages_per_slot`` bound with the hit length as a plain masked
ARGUMENT (one compile for any hit depth — the fixed-shape contract
``strict_compiles`` enforces).  graft-lint must stay quiet on every
function here.
"""

from functools import partial

import jax
import jax.numpy as jnp


def _adopt(cache, page_ids, n_shared):
    keep = jnp.arange(cache["block_tables"].shape[1]) < n_shared
    row = jnp.where(keep, page_ids, cache["block_tables"][0])
    return {"block_tables": cache["block_tables"].at[0].set(row),
            "seq_lens": cache["seq_lens"]}


jitted_adopt = jax.jit(_adopt, donate_argnums=(0,))


def adopt_reuses_donated_block_tables(cache, page_ids, n_shared):
    # the keep-count accounting reads the RETURNED structure: the donated
    # name is dead after the adopt dispatch (in production the scheduler's
    # host shared-prefix mirror does this arithmetic with no device fetch)
    new_cache = jitted_adopt(cache, page_ids, n_shared)
    keep_counts = (new_cache["block_tables"][0] >= 0).sum()
    return new_cache, keep_counts


@partial(jax.jit, static_argnames=("pages_per_slot",))
def adopt_mask_hit_iota(n_hit, x, pages_per_slot):
    """GL305 fixed: the width is the static ``pages_per_slot`` BOUND, not
    this admission's live hit length — hits of any depth pad up to it and
    mask, one compile ever."""
    return x + jnp.where(jnp.arange(pages_per_slot) < n_hit, 1, 0)


def example_args():
    cache = {
        "block_tables": jnp.zeros((4, 8), jnp.int32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "adopt_reuses_donated_block_tables": (
            cache, jnp.zeros((8,), jnp.int32), jnp.asarray(2, jnp.int32)
        ),
    }
