"""PLANTED prefix-cache hazards — the two ways COW page sharing breaks the
serving contracts (corrected twins: ``clean_prefix.py``).

Adoption writes a request's shared page ids into the donated cache's
block table; the tempting bug is reading the block-table row back off the
DONATED structure after the adopt dispatch to build the release keep
counts — ``adopt_reuses_donated_block_tables`` carries that shape (GL201,
the async-ckpt race applied across the share boundary; the real engine
reads the RETURNED cache, and its host ``shared_pages`` mirror needs no
device fetch at release time at all).
``adopt_mask_hit_iota`` carries the hit-length-dependent trace (GL305): an
adopt program keyed on this admission's matched-prefix length re-
specializes per hit depth — the first prompt with a different cached
prefix length would recompile mid-traffic (``strict_compiles``); the real
adopt program pads the id vector to the static ``pages_per_slot`` bound
and masks, one compile ever.  Excluded from repo-wide sweeps like the
rest of this directory.
"""

import jax
import jax.numpy as jnp


def _adopt(cache, page_ids, n_shared):
    keep = jnp.arange(cache["block_tables"].shape[1]) < n_shared
    row = jnp.where(keep, page_ids, cache["block_tables"][0])
    return {"block_tables": cache["block_tables"].at[0].set(row),
            "seq_lens": cache["seq_lens"]}


jitted_adopt = jax.jit(_adopt, donate_argnums=(0,))


def adopt_reuses_donated_block_tables(cache, page_ids, n_shared):
    # GL201: `cache` was donated to the adopt step — XLA may already be
    # overwriting its buffers in place when the keep-count accounting
    # reads block_tables off the STALE structure instead of the returned
    # one (the production engine keeps the shared prefix in the host
    # SlotState mirror: no device fetch on the release path)
    new_cache = jitted_adopt(cache, page_ids, n_shared)
    keep_counts = (cache["block_tables"][0] >= 0).sum()
    return new_cache, keep_counts


@jax.jit
def adopt_mask_hit_iota(hit_page_ids, x):
    """GL305: ``hit_page_ids.shape[0]`` (this admission's matched-prefix
    length) flows straight into ``jnp.arange`` and the hit length is not
    static — the adopt program re-specializes per hit depth instead of
    padding to the ``pages_per_slot`` bound (the mid-traffic recompile
    ``strict_compiles`` exists to catch)."""
    return x + jnp.arange(hit_page_ids.shape[0])


def example_args():
    cache = {
        "block_tables": jnp.zeros((4, 8), jnp.int32),
        "seq_lens": jnp.zeros((4,), jnp.int32),
    }
    return {
        "adopt_reuses_donated_block_tables": (
            cache, jnp.zeros((8,), jnp.int32), jnp.asarray(2, jnp.int32)
        ),
    }
