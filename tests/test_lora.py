"""Multi-tenant batched LoRA (ISSUE 9 / ROADMAP item 2): the segment-batched
adapter matmul (ops/lora.py), the hot-swap adapter pool
(serving/adapters.py), per-request routing in the serving engine, and the
per-adapter fine-tuning path.

The acceptance pins live here: batched multi-adapter decode is
BITWISE-identical to applying each request's adapter sequentially (mixed
ids in one batch, id-0 "no adapter" rows included, and under
eviction/hot-swap pressure), while the decode step stays ONE fixed-shape
donation-clean compiled program for any tenant mix (the replay harness
raises on any post-warmup compile)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate_paged
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.ops.lora import (
    adapter_param_count,
    adapter_state_accounting,
    bgmv,
    init_adapter_params,
    init_lora_pool,
    lora_apply,
    lora_apply_sequential,
    lora_spec,
)
from accelerate_tpu.serving import (
    AdapterPoolFullError,
    AdapterStore,
    ContinuousBatchingScheduler,
    LoraTrainer,
    Request,
    ServingEngine,
    adapter_pool_accounting,
    predicted_adapter_hit_rate,
    replay,
    synthesize_trace,
)
from accelerate_tpu.utils.dataclasses import LoraPlugin, ServingPlugin

GEN_CFG = GenerationConfig(max_new_tokens=6)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _lplug(**kw):
    kw.setdefault("rank", 4)
    kw.setdefault("pool_slots", 2)
    kw.setdefault("kernel", "native")
    return LoraPlugin(**kw)


def _splug(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("decode_kernel", "native")
    return ServingPlugin(**kw)


def _store(params, plugin, tenants, offload_dir=None):
    store = AdapterStore(params, plugin, offload_dir=offload_dir)
    for t in tenants:
        store.publish_random(t, jax.random.PRNGKey(100 + t))
    return store


# ---------------------------------------------------------------------------
# the op: batched == sequential, bitwise
# ---------------------------------------------------------------------------


def test_lora_apply_batched_bitwise_equals_sequential():
    """The tentpole pin at the op level: one gathered einsum over mixed
    adapter ids reproduces the per-row sequential schedule BITWISE — id-0
    rows come back as the untouched base output (a where-select, so even a
    negative zero survives), eagerly and under jit."""
    rng = np.random.default_rng(0)
    B, T, d, r, o, P = 6, 3, 16, 4, 24, 3
    x = jnp.asarray(rng.normal(size=(B, T, d)), jnp.bfloat16)
    y = jnp.asarray(rng.normal(size=(B, T, o)), jnp.bfloat16)
    y = y.at[0, 0, 0].set(jnp.bfloat16(-0.0))  # the sign-bit witness
    a = jnp.asarray(rng.normal(size=(P + 1, d, r)), jnp.bfloat16).at[0].set(0)
    b = jnp.asarray(rng.normal(size=(P + 1, r, o)), jnp.bfloat16).at[0].set(0)
    ids = jnp.asarray([0, 1, 3, 1, 2, 0], jnp.int32)

    out = lora_apply(x, y, a, b, ids, kernel="native")
    ref = lora_apply_sequential(x, y, a, b, ids)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # id-0 rows: bitwise the base output, sign bit of -0.0 included
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(y[0]))
    assert np.signbit(np.asarray(out, np.float32))[0, 0, 0]
    # under jit (the serving path): same bits
    out_jit = jax.jit(lambda *a_: lora_apply(*a_, kernel="native"))(x, y, a, b, ids)
    np.testing.assert_array_equal(np.asarray(out_jit), np.asarray(out))
    # 2-D rows (LMHead / per-token routing shape)
    out2 = lora_apply(x[:, 0], y[:, 0], a, b, ids, kernel="native")
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(out[:, 0]))


def test_bgmv_kernel_matches_native(tiny_model):
    """The Pallas gather-matmul decode kernel (interpret mode off-TPU) ==
    the gathered-einsum math, fp32-accumulated, mixed ids included."""
    rng = np.random.default_rng(1)
    S, d, r, o, P = 5, 32, 4, 48, 3
    x = jnp.asarray(rng.normal(size=(S, d)), jnp.float32)
    a = jnp.asarray(rng.normal(size=(P + 1, d, r)), jnp.float32).at[0].set(0)
    b = jnp.asarray(rng.normal(size=(P + 1, r, o)), jnp.float32).at[0].set(0)
    ids = np.asarray([0, 2, 1, 3, 2], np.int32)
    out = np.asarray(bgmv(x, a, b, jnp.asarray(ids)))
    ref = np.stack([
        (np.asarray(x)[i] @ np.asarray(a)[ids[i]]) @ np.asarray(b)[ids[i]]
        for i in range(S)
    ])
    np.testing.assert_allclose(out, ref, atol=2e-5)
    # dispatch through lora_apply(kernel="bgmv") keeps id-0 rows bitwise
    y = jnp.asarray(rng.normal(size=(S, 1, o)), jnp.float32)
    full = lora_apply(x[:, None], y, a, b, jnp.asarray(ids), kernel="bgmv")
    np.testing.assert_array_equal(np.asarray(full[0]), np.asarray(y[0]))


def test_lora_model_mixed_batch_bitwise(tiny_model):
    """Through the real model: a mixed-id batch row is bitwise-identical to
    the same row in a single-tenant (all-one-id) pass, and id-0 rows are
    bitwise the base forward."""
    model, params = tiny_model
    spec = lora_spec(params)
    pool = init_lora_pool(spec, pool_slots=3, rank=4, dtype=model.config.dtype)
    ad = init_adapter_params(jax.random.PRNGKey(1), spec, 4, init_b="normal",
                             dtype=model.config.dtype)
    pool = jax.tree_util.tree_map(lambda p, a: p.at[2].set(a), pool, ad)
    x = jnp.asarray(np.random.default_rng(0).integers(1, 255, (3, 8)), jnp.int32)
    base = model.apply(params, x)
    mixed = model.apply({**params, "lora": pool}, x,
                        adapter_ids=jnp.asarray([0, 2, 0], jnp.int32))
    solo = model.apply({**params, "lora": pool}, x,
                       adapter_ids=jnp.asarray([2, 2, 2], jnp.int32))
    np.testing.assert_array_equal(np.asarray(mixed[0]), np.asarray(base[0]))
    np.testing.assert_array_equal(np.asarray(mixed[2]), np.asarray(base[2]))
    np.testing.assert_array_equal(np.asarray(mixed[1]), np.asarray(solo[1]))
    assert not np.array_equal(np.asarray(mixed[1]), np.asarray(base[1]))


# ---------------------------------------------------------------------------
# the pool: LRU hot-swap, refcount pinning, donation
# ---------------------------------------------------------------------------


def test_adapter_pool_lru_and_refcount_pinning(tiny_model):
    """Pool pressure evicts the LRU *unpinned* adapter only: a slot held by
    an in-flight request survives any number of swaps around it, the
    swapped-in stack holds exactly the published factors, and a fully
    pinned pool refuses (AdapterPoolFullError) instead of evicting."""
    model, params = tiny_model
    store = _store(params, _lplug(pool_slots=2), (1, 2, 3))
    published2 = store._host_tree(2)

    s1, sw1 = store.pin(1)
    s2, sw2 = store.pin(2)
    assert sw1 and sw2 and {s1, s2} == {1, 2}
    # the resident stack row IS the published adapter
    flat_pool = {}

    def collect(path, leaf):
        flat_pool["/".join(str(getattr(k, "key", k)) for k in path)] = leaf

    jax.tree_util.tree_map_with_path(collect, store.pool)
    for key, host in published2.items():
        np.testing.assert_array_equal(np.asarray(flat_pool[key][s2]),
                                      np.asarray(host))
    # the null slot stays zeros through every swap (the id-0 invariant)
    assert all(not np.asarray(leaf[0]).any() for leaf in flat_pool.values())
    # both pinned: nothing evictable
    assert not store.can_pin(3)
    with pytest.raises(AdapterPoolFullError):
        store.pin(3)
    # unpin 1 -> it becomes the LRU victim; 2 (still pinned) survives
    store.unpin(1)
    s3, sw3 = store.pin(3)
    assert sw3 and s3 == s1
    assert not store.resident(1) and store.resident(2)
    # re-pin of a resident adapter is a hit, not a swap
    s2b, sw2b = store.pin(2)
    assert s2b == s2 and not sw2b
    assert store.hits == 1 and store.swaps == 3
    assert store.swap_bytes == 3 * sum(
        leaf.size * leaf.dtype.itemsize for leaf in published2.values()
    )
    # shared-adapter refcount: tenant 2 holds TWO in-flight requests — one
    # retire leaves it pinned, so only re-unpinning frees it for LRU
    store.unpin(2)
    assert store.refcount.get(2, 0) == 1
    assert store._evictable() is None  # 2 and 3 both still pinned
    store.unpin(2)
    store.unpin(3)
    assert store._evictable() == 3  # LRU order: 2 was used (re-pinned) last

    # RE-publish of a resident tenant refreshes its slot in place (and
    # never serves a stale staged prefetch): continuous fine-tuning must
    # not keep decoding with the old weights until LRU luck evicts them
    from accelerate_tpu.serving.adapters import _flatten as _flat

    fresh = init_adapter_params(jax.random.PRNGKey(99), store.spec, 4,
                                init_b="normal", dtype=store.dtype)
    store.publish(2, fresh)
    jax.tree_util.tree_map_with_path(collect, store.pool)
    for key, leaf in _flat(fresh).items():
        np.testing.assert_array_equal(np.asarray(flat_pool[key][s2]),
                                      np.asarray(leaf))


def test_adapter_prefetch_streams_before_pin(tiny_model):
    """Explicit prefetch (the scheduler's waiting-queue lookahead) stages
    the H2D upload early; the later pin is a prefetch HIT in the stream
    stats — the hot-swap analog of the layer-prefetch double buffer."""
    model, params = tiny_model
    store = _store(params, _lplug(), (1, 2))
    assert store.prefetch(1)
    assert not store.prefetch(1)   # already in flight
    store.pin(1)
    assert store.stats.prefetch_hits == 1
    # resident adapters never re-stage
    assert not store.prefetch(1)


def test_predicted_hit_rate_lru_replay():
    assert predicted_adapter_hit_rate([], 2) == 0.0
    assert predicted_adapter_hit_rate([0, 0], 2) == 0.0
    # 1,2 miss; 1 hit; 3 miss evicts 2; 2 miss again
    assert predicted_adapter_hit_rate([1, 2, 1, 3, 2], 2) == 0.2
    # pool >= tenants: only compulsory misses
    assert predicted_adapter_hit_rate([1, 2, 1, 2, 1], 2) == 0.6


def test_adapter_accounting_ladders(tiny_model):
    model, params = tiny_model
    spec = lora_spec(params)
    n = adapter_param_count(spec, 4)
    assert n == sum(4 * (di + do) for di, do in spec.values())
    acct = adapter_state_accounting(spec, 4, 10_000, optimizer="lion-sr8")
    assert acct["params_per_adapter"] == n
    assert acct["state_bytes_per_adapter"] == int(n * 8.1)  # the -sr8 ladder row
    assert acct["adapters_per_host"]["256GiB"] > acct["adapters_per_host"]["64GiB"]
    pool = adapter_pool_accounting(spec, rank=4, pool_slots=8, decode_step_s=0.005)
    assert pool["pool_bytes"] == pool["bytes_per_slot"] * 9
    assert 0.0 <= pool["swap_overlap_frac_pred"] <= 1.0


# ---------------------------------------------------------------------------
# serving: routing, parity under pressure, one compiled program
# ---------------------------------------------------------------------------


def test_serve_with_adapters_matches_per_request_reference(tiny_model, tmp_path):
    """THE acceptance pin: a mixed-tenant trace served batched — WITH
    hot-swap pressure (pool smaller than the tenant set) AND page-pressure
    evictions — emits per request exactly the tokens of a dedicated
    single-request pass through ``generate_paged`` with that adapter (the
    sequential reference), while the whole replay runs zero post-warmup
    compiles (``strict_compiles`` raises otherwise) and the decode step
    audits donation-clean."""
    model, params = tiny_model
    lplug = _lplug(pool_slots=2)
    store = _store(params, lplug, (1, 2, 3), offload_dir=str(tmp_path / "cold"))
    splug = ServingPlugin(num_slots=4, page_size=2, pages_per_slot=10,
                          num_pages=14, prefill_chunk=8, decode_kernel="native")
    trace = synthesize_trace(3, 7, vocab_size=255, prompt_len_range=(3, 9),
                             new_tokens_range=(3, 6), adapters=3)
    assert len({r.adapter_id for r in trace if r.adapter_id}) >= 2
    eng = ServingEngine(model, params, splug, GEN_CFG, adapters=store)
    rep = replay(eng, trace)  # strict_compiles=True: raises on any recompile
    assert rep["completed"] == len(trace)
    assert rep["adapter_swaps"] > 0          # hot-swap pressure was real
    assert rep["evictions"] > 0              # page-pressure eviction too
    assert rep["compiles_measured"] == 0
    assert eng.free_page_mirror_in_sync()

    ref_store = _store(params, lplug, (1, 2, 3))
    for r in trace:
        out = generate_paged(
            model, params, jnp.asarray([r.prompt], jnp.int32),
            GenerationConfig(max_new_tokens=r.max_new_tokens),
            serving_plugin=_splug(), adapters=ref_store,
            adapter_ids=[r.adapter_id],
        )
        ref = [int(x) for x in np.asarray(out[0])][: len(rep["results"][r.uid])]
        assert rep["results"][r.uid] == ref, f"request {r.uid} (tenant {r.adapter_id})"

    audit = eng.audit_decode_step(default_memory_kind="device")
    assert not audit.unsuppressed(), audit.render()


def test_adapter_trace_determinism(tiny_model):
    """Same seed -> same multi-tenant trace -> identical schedule
    (swap/bypass events included) and identical tokens."""
    model, params = tiny_model

    def run():
        store = _store(params, _lplug(pool_slots=2), (1, 2, 3))
        trace = synthesize_trace(5, 6, vocab_size=255, prompt_len_range=(3, 8),
                                 new_tokens_range=(2, 5), adapters=3)
        eng = ServingEngine(model, params, _splug(), GEN_CFG, adapters=store)
        results = eng.run(trace)
        return eng.sched.events, results

    ev_a, res_a = run()
    ev_b, res_b = run()
    assert ev_a == ev_b and res_a == res_b
    assert any(e[0] == "swap" for e in ev_a)


def test_unpublished_adapter_rejected(tiny_model):
    model, params = tiny_model
    store = _store(params, _lplug(), (1,))
    eng = ServingEngine(model, params, _splug(), GEN_CFG, adapters=store)
    with pytest.raises(ValueError, match="never published"):
        eng.add_request(Request(uid=0, prompt=(3, 4), max_new_tokens=2,
                                adapter_id=9))
    eng2 = ServingEngine(model, params, _splug(), GEN_CFG)
    with pytest.raises(ValueError, match="no AdapterStore"):
        eng2.add_request(Request(uid=0, prompt=(3, 4), max_new_tokens=2,
                                 adapter_id=1))


# ---------------------------------------------------------------------------
# admission fairness: bounded-age bypass (the satellite, pinned)
# ---------------------------------------------------------------------------


def test_admission_bounded_age_bypass_prevents_starvation(tiny_model):
    """Deterministic trace: a head-of-line tenant blocked on adapter-pool
    contention is bypassed by zero-swap arrivals for EXACTLY
    ``max_bypass_age`` ticks, then admission holds the line until the
    starved tenant's pin succeeds — with strict FIFO (age 0) no bypass
    ever happens.  Pinned event-for-event."""
    model, params = tiny_model
    store = _store(params, _lplug(pool_slots=1, max_bypass_age=2), (1, 2))
    sched = ContinuousBatchingScheduler(
        num_slots=2, num_pages=64, page_size=4, pages_per_slot=8,
        prefill_chunk=8, prefill_buckets=(8,), adapters=store,
        max_bypass_age=2,
    )
    # tenant 1 occupies the single pool slot via an in-flight request
    sched.submit(Request(uid=0, prompt=(1, 2), max_new_tokens=2, adapter_id=1))
    assert sched.admit() == [0]
    # head-of-line: tenant 2 (needs the pinned slot) + zero-swap arrivals
    sched.submit(Request(uid=1, prompt=(1, 2), max_new_tokens=2, adapter_id=2))
    for uid in (2, 3, 4):
        sched.submit(Request(uid=uid, prompt=(1, 2), max_new_tokens=2))

    admitted_uids = []
    for tick in range(4):
        new = sched.admit()
        admitted_uids.extend(sched.slots[s].request.uid for s in new)
        for s in new:  # retire the bypasser: frees its slot for the next tick
            if sched.slots[s].request.adapter_id == 0:
                sched.slots[s].prefilled = 2
                sched.slots[s].tokens = [0, 0]
                sched.finish(s)
    # ticks 1..2: bypass allowed (uid 2 then 3); tick 3+: line held for uid 1
    assert admitted_uids == [2, 3]
    assert [e for e in sched.events if e[0] == "bypass"] == \
        [("bypass", 2, 1), ("bypass", 3, 1)]
    # the head is starving no longer once tenant 1's request retires
    sched.slots[0].prefilled = 2
    sched.slots[0].tokens = [0, 0]
    sched.finish(0)
    new = sched.admit()
    uids = [sched.slots[s].request.uid for s in new]
    assert uids[0] == 1  # the starved tenant admits FIRST
    assert ("swap", 2, 1) in sched.events

    # strict FIFO (max_bypass_age=0): zero bypass events, ever
    store2 = _store(params, _lplug(pool_slots=1, max_bypass_age=0), (1, 2))
    sched2 = ContinuousBatchingScheduler(
        num_slots=2, num_pages=64, page_size=4, pages_per_slot=8,
        prefill_chunk=8, prefill_buckets=(8,), adapters=store2,
        max_bypass_age=0,
    )
    sched2.submit(Request(uid=0, prompt=(1, 2), max_new_tokens=2, adapter_id=1))
    sched2.admit()
    sched2.submit(Request(uid=1, prompt=(1, 2), max_new_tokens=2, adapter_id=2))
    sched2.submit(Request(uid=2, prompt=(1, 2), max_new_tokens=2))
    for _ in range(3):
        assert sched2.admit() == []
    assert not [e for e in sched2.events if e[0] == "bypass"]


# ---------------------------------------------------------------------------
# fine-tuning: batched grads, host state, verified checkpoints
# ---------------------------------------------------------------------------


def test_lora_trainer_batched_step_and_verified_checkpoint(tiny_model, tmp_path):
    """One batched mixed-tenant step: loss matches the per-adapter
    sequential schedule, only the gathered tenants' adapters move, the
    per-adapter int8-SR optimizer state round-trips BIT-EXACTLY through
    the verified-checkpoint path (manifest + tmp-stage + os.replace), a
    restored trainer continues bit-identically, and a torn save raises
    instead of resuming wrong tenants."""
    from accelerate_tpu.checkpointing import CheckpointCorruptError

    model, params = tiny_model
    trainer = LoraTrainer(model, params, _lplug(pool_slots=3, optimizer="lion-sr8"))
    for t in (1, 2, 3):
        trainer.add_adapter(t)
    untouched_before = jax.tree_util.tree_leaves(trainer.adapters[3])
    rng = np.random.default_rng(2)
    toks = jnp.asarray(rng.integers(1, 255, (4, 8)), jnp.int32)
    batch = {"input_ids": toks, "labels": toks}
    seq_loss = trainer.sequential_loss(batch, [1, 2, 0, 1])
    loss = trainer.step(batch, [1, 2, 0, 1])
    assert np.isclose(loss, seq_loss, rtol=1e-2)
    # tenant 3 took no rows: its adapter and state must be untouched
    for before, after in zip(untouched_before,
                             jax.tree_util.tree_leaves(trainer.adapters[3])):
        np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
    rep = trainer.host_state_report()
    assert rep["n_adapters"] == 3 and rep["state_bytes"] > 0

    ck = tmp_path / "adapters_ck"
    trainer.save(str(ck))
    assert not (tmp_path / "adapters_ck.tmp").exists()  # atomic publish
    restored = LoraTrainer(model, params, _lplug(pool_slots=3, optimizer="lion-sr8"))
    assert restored.load(str(ck)) == [1, 2, 3]
    for t in (1, 2, 3):
        for a, b in zip(jax.tree_util.tree_leaves(trainer.adapters[t]),
                        jax.tree_util.tree_leaves(restored.adapters[t])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(trainer.opt_states[t]),
                        jax.tree_util.tree_leaves(restored.opt_states[t])):
            np.testing.assert_array_equal(
                np.asarray(LoraTrainer._npz_safe(a)),
                np.asarray(LoraTrainer._npz_safe(b)))
    # bitwise-identical continuation
    assert trainer.step(batch, [1, 2, 0, 1]) == restored.step(batch, [1, 2, 0, 1])
    # periodic checkpointing: a SECOND save to the same directory
    # republishes cleanly (os.replace cannot overwrite a non-empty dir —
    # the finalize discipline clears it first), and still verifies
    trainer.save(str(ck))
    assert LoraTrainer(model, params,
                       _lplug(pool_slots=3, optimizer="lion-sr8")).load(str(ck)) == [1, 2, 3]

    # torn save: truncate a shard -> the crc32 manifest gate raises
    shard = sorted(ck.glob("adapter_*.npz"))[0]
    shard.write_bytes(shard.read_bytes()[:-16])
    with pytest.raises(CheckpointCorruptError):
        LoraTrainer(model, params, _lplug(pool_slots=3)).load(str(ck))


# ---------------------------------------------------------------------------
# plugin knobs
# ---------------------------------------------------------------------------


def test_lora_plugin_env_defaults(monkeypatch):
    monkeypatch.setenv("ACCELERATE_LORA_RANK", "16")
    monkeypatch.setenv("ACCELERATE_LORA_POOL_SLOTS", "7")
    monkeypatch.setenv("ACCELERATE_LORA_TARGETS", "q_proj, o_proj")
    monkeypatch.setenv("ACCELERATE_LORA_KERNEL", "bgmv")
    monkeypatch.setenv("ACCELERATE_LORA_BYPASS_AGE", "5")
    p = LoraPlugin()
    assert (p.rank, p.pool_slots, p.kernel, p.max_bypass_age) == (16, 7, "bgmv", 5)
    assert p.targets == ("q_proj", "o_proj")
    # explicit arguments always win over env
    assert LoraPlugin(rank=2).rank == 2
    with pytest.raises(ValueError):
        LoraPlugin(kernel="mystery")
    with pytest.raises(ValueError):
        LoraPlugin(rank=0)
    with pytest.raises(ValueError):
        LoraPlugin(pool_slots=0)
    with pytest.raises(ValueError):
        LoraPlugin(max_bypass_age=-1)
    with pytest.raises(ValueError):
        LoraPlugin(targets=())
