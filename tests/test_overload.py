"""Serving resilience tests: overload control, request deadlines,
deterministic cancellation, the SLO degradation ladder, and chaos replay
(ISSUE 14).  The acceptance pins: under any seeded ``FaultPlan`` of serving
faults the surviving requests' greedy tokens are BITWISE identical to a
fault-free replay of the same surviving set, ``verify_serving_invariants``
holds after every scenario (free-page mirror exact, adapter refcounts
balanced, zero leaked pages), and ``strict_compiles`` holds through the
full degradation ladder post-warmup.

Every engine in this module shares ONE geometry (slots=4, page=4, pool=24,
chunk=8) so the process-shared jit cache compiles each program exactly
once for the whole file — the tier-1 time-budget discipline.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.generation import GenerationConfig, generate
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.resilience import FaultEvent, FaultPlan, fault_plan
from accelerate_tpu.serving import (
    Request,
    ServingEngine,
    chaos_replay,
    replay,
    synthesize_trace,
    verify_serving_invariants,
)
from accelerate_tpu.telemetry import SLOMonitor, twin_registry
from accelerate_tpu.utils.dataclasses import ServingPlugin

MAX_NEW = 16  # ONE decode budget for the module: every engine shares jits


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _plugin(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("decode_kernel", "native")
    return ServingPlugin(**kw)


def _engine(tiny_model, **kw):
    model, params = tiny_model
    return ServingEngine(model, params, _plugin(**kw),
                         GenerationConfig(max_new_tokens=MAX_NEW))


def _ref_tokens(tiny_model, prompt, n):
    model, params = tiny_model
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   GenerationConfig(max_new_tokens=n))
    return [int(x) for x in out[0]]


def _prompts(seed, lengths):
    rng = np.random.default_rng(seed)
    return [tuple(int(x) for x in rng.integers(1, 255, n)) for n in lengths]


def _assert_clean(eng):
    problems = verify_serving_invariants(eng)
    assert not problems, problems


# ---------------------------------------------------------------------------
# the regression first (satellite): remaining_requests after a drain with a
# cancelled-but-not-yet-retired request — exactly once, never twice or zero
# ---------------------------------------------------------------------------


def test_remaining_requests_pending_cancel_exactly_once(tiny_model):
    """A cancel issued between ticks is processed at the NEXT tick boundary;
    a preemption drain arriving first must hand the request back exactly
    once (it was never retired), with no duplicate across the in-flight /
    queued / undelivered union — and a PROCESSED cancel must never come
    back."""
    eng = _engine(tiny_model)
    prompts = _prompts(0, (6, 6, 6, 6, 6))
    for i, p in enumerate(prompts):
        eng.add_request(Request(uid=i, prompt=p, max_new_tokens=MAX_NEW))
    for _ in range(4):
        eng.step()
    victim = eng.unfinished_requests()[0].uid
    eng.cancel(victim)  # pending: the drain below beats the tick boundary
    plan = FaultPlan([FaultEvent("preempt", at=1, site="serve_step")])
    with fault_plan(plan):
        eng.step()
    assert eng.interrupted and plan.fired
    remaining = [r.uid for r in eng.remaining_requests()]
    assert remaining.count(victim) == 1
    assert len(remaining) == len(set(remaining))
    assert set(remaining) | set(eng.results) == set(range(len(prompts)))

    # the processed-cancel side: a fresh engine that applies the cancel
    # before draining must NOT hand the cancelled request back
    eng2 = _engine(tiny_model)
    for i, p in enumerate(prompts):
        eng2.add_request(Request(uid=i, prompt=p, max_new_tokens=MAX_NEW))
    for _ in range(4):
        eng2.step()
    victim2 = eng2.unfinished_requests()[0].uid
    eng2.cancel(victim2)
    eng2.step()  # tick boundary processes the cancel
    assert victim2 in eng2.sched.retired_uids
    plan2 = FaultPlan([FaultEvent("preempt", at=1, site="serve_step")])
    with fault_plan(plan2):
        eng2.step()
    remaining2 = [r.uid for r in eng2.remaining_requests()]
    assert victim2 not in remaining2
    assert len(remaining2) == len(set(remaining2))


# ---------------------------------------------------------------------------
# cancellation: every lifecycle stage, every resource provably released
# ---------------------------------------------------------------------------


def test_cancel_releases_resources_at_every_stage(tiny_model):
    """Cancel a queued request, a mid-prefill-chunk request and a decoding
    request; after each the full invariant contract holds and the OTHER
    requests still emit their exact solo-run tokens."""
    eng = _engine(tiny_model)
    prompts = _prompts(1, (6, 13, 5, 5, 5))  # uid 1 needs 2 prefill chunks
    for i, p in enumerate(prompts):
        eng.add_request(Request(uid=i, prompt=p, max_new_tokens=MAX_NEW))
    eng.step()  # admit + first prefill
    # uid 4 is queued (4 slots); cancel it while queued
    assert 4 in {r.uid for r in eng.sched.waiting}
    eng.cancel(4)
    eng.step()
    assert 4 in eng.sched.retired_uids
    _assert_clean(eng)
    # uid 1 (13-token prompt, chunk 8) is mid-prefill after its first chunk;
    # drive until that chunk lands, then cancel it mid-prefill
    while not any(st.request.uid == 1 and 0 < st.prefilled < 13
                  for st in eng.sched.slots.values()):
        eng.step()
    before = eng.sched.pages_reclaimed_on_cancel
    eng.cancel(1)
    eng.step()
    assert 1 in eng.sched.retired_uids
    assert eng.sched.pages_reclaimed_on_cancel > before  # prefix pages freed
    _assert_clean(eng)
    # cancel uid 0 once it is decoding (has emitted at least one token)
    while not any(st.request.uid == 0 and st.tokens
                  for st in eng.sched.slots.values()):
        eng.step()
    eng.cancel(0)
    eng.step()
    assert 0 in eng.sched.retired_uids
    _assert_clean(eng)
    while not eng.idle():
        eng.step()
    _assert_clean(eng)
    stages = {ev[1]: ev[2] for ev in eng.sched.events if ev[0] == "cancel"}
    assert stages == {4: "queued", 1: "prefill", 0: "decode"}
    assert eng.sched.cancelled == 3
    for uid in (2, 3):  # the survivors: bitwise solo-run tokens
        assert eng.results[uid] == _ref_tokens(tiny_model, prompts[uid], MAX_NEW)
    for uid in (0, 1, 4):
        assert uid not in eng.results


def test_cancel_mid_speculative_verify_rolls_back_exactly(tiny_model):
    """With speculation on, a cancelled request's pages include the KV the
    verify passes already wrote (``kv_len`` beyond the host stream) — the
    release must follow the device, and survivors keep bitwise parity."""
    eng = _engine(tiny_model, speculate="ngram", speculate_k=4)
    prompts = _prompts(2, (6, 7, 8))
    for i, p in enumerate(prompts):
        eng.add_request(Request(uid=i, prompt=p, max_new_tokens=MAX_NEW))
    while eng.metrics["verify_steps"] == 0:
        eng.step()
    live = [st.request.uid for st in eng.sched.slots.values() if st.tokens]
    victim = live[0]
    eng.cancel(victim)
    eng.step()
    assert victim in eng.sched.retired_uids
    _assert_clean(eng)
    while not eng.idle():
        eng.step()
    _assert_clean(eng)
    for uid in range(3):
        if uid == victim:
            assert uid not in eng.results
        else:
            assert eng.results[uid] == _ref_tokens(tiny_model, prompts[uid],
                                                   MAX_NEW)


# ---------------------------------------------------------------------------
# deadlines + shed policy
# ---------------------------------------------------------------------------


def test_deadline_retires_queued_and_inflight(tiny_model):
    """An expired queued request sheds (reason ``deadline``), an expired
    in-flight request cancels (reason ``deadline``); both count as
    deadline_misses, resources come back, survivors keep parity."""
    eng = _engine(tiny_model)
    prompts = _prompts(3, (6, 6, 6, 6, 6, 6))
    # uids 0-3 fill the slots with no deadline; uid 4 queues with a deadline
    # it cannot make; uid 5 queues without one
    for i in range(4):
        eng.add_request(Request(uid=i, prompt=prompts[i], max_new_tokens=MAX_NEW))
    eng.add_request(Request(uid=4, prompt=prompts[4], max_new_tokens=MAX_NEW,
                            deadline_ticks=2))
    eng.add_request(Request(uid=5, prompt=prompts[5], max_new_tokens=MAX_NEW))
    for _ in range(4):
        eng.step()
    # in-flight expiry: give uid 0 a post-hoc storm via an explicit deadline
    # fault (every live request expires; survivors are later arrivals)
    while not eng.idle():
        eng.step()
    assert ("shed", 4, "deadline") in eng.sched.events
    assert eng.sched.deadline_misses >= 1
    assert 4 not in eng.results
    _assert_clean(eng)
    assert eng.results[5] == _ref_tokens(tiny_model, prompts[5], MAX_NEW)

    # in-flight: a request whose deadline lands mid-decode cancels at stage
    # "prefill"/"decode" with its pages reclaimed
    eng2 = _engine(tiny_model)
    eng2.add_request(Request(uid=0, prompt=prompts[0], max_new_tokens=MAX_NEW,
                             deadline_ticks=6))
    eng2.add_request(Request(uid=1, prompt=prompts[1], max_new_tokens=MAX_NEW))
    while not eng2.idle():
        eng2.step()
    cancels = [ev for ev in eng2.sched.events if ev[0] == "cancel"]
    assert cancels and cancels[0][1] == 0 and cancels[0][3] == "deadline"
    assert eng2.sched.deadline_misses == 1
    assert eng2.sched.pages_reclaimed_on_cancel > 0
    assert 0 not in eng2.results
    assert eng2.results[1] == _ref_tokens(tiny_model, prompts[1], MAX_NEW)
    _assert_clean(eng2)


def test_shed_policy_bounded_queue_and_watermark(tiny_model):
    """The bounded queue sheds deterministically — oldest-beyond-deadline
    first, else the youngest arrival — and the KV-pressure watermark sheds
    queued demand down to the mark without ever touching admitted work."""
    eng = _engine(tiny_model, max_queue=2)
    prompts = _prompts(4, (6,) * 8)
    for i in range(4):  # the bound holds at the submit door too: admit in
        eng.add_request(Request(uid=i, prompt=prompts[i], max_new_tokens=MAX_NEW))
        if i % 2:
            eng.step()  # drain the line into the four free slots pairwise
    for i in range(4, 8):
        eng.add_request(Request(uid=i, prompt=prompts[i], max_new_tokens=MAX_NEW,
                                arrival_step=i))
    # queue bound 2 → the youngest arrivals shed at the submit door (no
    # deadlines: the newcomer backs off)
    sheds = [ev for ev in eng.sched.events if ev[0] == "shed"]
    assert [s[1] for s in sheds] == [6, 7]
    assert all(s[2] == "queue" for s in sheds)
    assert eng.sched.requests_shed == 2
    while not eng.idle():
        eng.step()
    _assert_clean(eng)
    for uid in range(6):
        assert eng.results[uid] == _ref_tokens(tiny_model, prompts[uid], MAX_NEW), uid

    # oldest-beyond-deadline first: an expired head sheds before a fresh
    # newcomer even though the newcomer is youngest
    eng2 = _engine(tiny_model, max_queue=1)
    eng2.sched.tick = 100  # virtual time flies past uid 20's deadline
    eng2.add_request(Request(uid=20, prompt=prompts[4], max_new_tokens=MAX_NEW,
                             deadline_ticks=1))
    eng2.add_request(Request(uid=21, prompt=prompts[5], max_new_tokens=MAX_NEW))
    shed_uids = [ev[1] for ev in eng2.sched.events if ev[0] == "shed"]
    assert shed_uids == [20]  # the expired head, not the newcomer

    # KV-pressure watermark: queued prompt demand beyond the mark sheds
    eng3 = _engine(tiny_model, kv_shed_watermark=0.5)
    for i in range(8):
        eng3.add_request(Request(uid=30 + i, prompt=prompts[i],
                                 max_new_tokens=MAX_NEW))
    eng3.step()
    assert eng3.sched.requests_shed > 0
    assert any(ev[2] == "kv_pressure" for ev in eng3.sched.events
               if ev[0] == "shed")
    while not eng3.idle():
        eng3.step()
    _assert_clean(eng3)


# ---------------------------------------------------------------------------
# determinism: the event log including cancel/shed/ladder entries
# ---------------------------------------------------------------------------


def test_scheduler_determinism_extends_to_chaos_events(tiny_model):
    """Same seed + same FaultPlan → identical event log including the new
    ``("cancel", ...)`` / ``("shed", ...)`` / ``("ladder", ...)`` entries
    and identical surviving tokens; a different fault seed schedules
    differently.  Invariants hold after every run."""
    def run(trace_seed, fault_seed):
        trace = synthesize_trace(trace_seed, 8, vocab_size=255,
                                 prompt_len_range=(3, 10),
                                 new_tokens_range=(2, 6),
                                 deadline_range=(4, 40))
        plan = FaultPlan([FaultEvent("cancel", at=4 + fault_seed),
                          FaultEvent("deadline", at=9 + fault_seed)])
        eng = _engine(tiny_model)
        with fault_plan(plan):
            results = eng.run(trace)
        _assert_clean(eng)
        return eng.sched.events, results

    ev_a, res_a = run(7, 0)
    ev_b, res_b = run(7, 0)
    assert ev_a == ev_b
    assert res_a == res_b
    kinds = {ev[0] for ev in ev_a}
    assert "cancel" in kinds and "ladder" in kinds
    ev_c, _ = run(7, 3)
    assert ev_c != ev_a


# ---------------------------------------------------------------------------
# chaos replay: the soak pin
# ---------------------------------------------------------------------------


def test_chaos_replay_surviving_tokens_bitwise(tiny_model):
    """The tentpole acceptance pin: a seeded FaultPlan of cancellation
    storms, deadline storms and serve-step preempts replays through
    drain-and-restart; surviving requests' tokens are BITWISE identical to
    a fault-free replay of the same surviving set, every engine life passes
    the invariant sweep, and zero post-warmup compiles fire."""
    trace = synthesize_trace(11, 10, vocab_size=255,
                             prompt_len_range=(3, 10), new_tokens_range=(2, 8))
    plan = FaultPlan.from_seed(5, 40, p_cancel=0.08, p_deadline=0.04,
                               p_preempt=0.05, serving=True)
    assert plan.events  # the seed actually arms something
    rep = chaos_replay(lambda: _engine(tiny_model), trace, plan)
    assert rep["token_parity"]
    assert rep["invariant_problems"] == []
    assert rep["compiles_measured"] == 0
    assert rep["faults_fired"] > 0
    disposed = (rep["completed"] + rep["requests_shed"] + rep["cancelled"]
                + rep["deadline_misses"])
    assert disposed >= rep["requests"]  # every request accounted for

    # with admission control ARMED the parity pin still holds: the
    # fault-free baseline disarms its own overload knobs, so survivors the
    # chaos run completed can never be shed/expired by the baseline's
    # policy (the spurious-parity-failure regression)
    rep2 = chaos_replay(
        lambda: _engine(tiny_model, max_queue=3, default_deadline_ticks=60),
        trace, FaultPlan.from_seed(5, 40, p_cancel=0.08, p_deadline=0.04,
                                   p_preempt=0.05, serving=True),
    )
    assert rep2["token_parity"]
    assert rep2["invariant_problems"] == []


def test_preempt_mid_verify_drains_clean_and_resumes(tiny_model):
    """A preempt armed at the ``verify_step`` site drains the engine before
    the pass dispatches: invariants hold at the drain, and a fresh engine
    finishing the remainder reproduces the uninterrupted tokens."""
    trace = synthesize_trace(13, 6, vocab_size=255,
                             prompt_len_range=(4, 10), new_tokens_range=(4, 10))
    full = _engine(tiny_model, speculate="ngram", speculate_k=4).run(trace)

    eng = _engine(tiny_model, speculate="ngram", speculate_k=4)
    plan = FaultPlan([FaultEvent("preempt", at=3, site="verify_step")])
    with fault_plan(plan):
        partial = eng.run(trace)
    assert eng.interrupted and plan.fired
    _assert_clean(eng)
    remaining = eng.remaining_requests()
    assert set(partial) | {r.uid for r in remaining} == {r.uid for r in trace}
    resumed = _engine(tiny_model, speculate="ngram", speculate_k=4).run([
        Request(uid=r.uid, prompt=r.prompt, max_new_tokens=r.max_new_tokens)
        for r in remaining
    ])
    assert {**partial, **resumed} == full


# ---------------------------------------------------------------------------
# the degradation ladder
# ---------------------------------------------------------------------------


def test_ladder_full_escalation_holds_strict_compiles(tiny_model):
    """Escalating through all four stages mid-traffic changes scheduling,
    never tokens: despeculate stops verify passes, prefill chunks clamp to
    the smallest warmed bucket, admission tightens, shed arms — with ZERO
    post-warmup compiles (every stage reuses warmed programs) and bitwise
    token parity for everything that completes."""
    eng = _engine(tiny_model, speculate="ngram", speculate_k=4)
    eng.warmup()
    before = eng.compile_events
    prompts = _prompts(6, (9, 10, 11, 9, 10, 9))
    pending = [Request(uid=i, prompt=p, max_new_tokens=MAX_NEW)
               for i, p in enumerate(prompts)]
    i = 0
    while not (eng.idle() and i >= len(pending)):
        while i < len(pending) and pending[i].arrival_step <= eng.steps:
            eng.add_request(pending[i])
            i += 1
        if eng.steps == 5:
            for _ in range(4):
                eng.ladder.escalate()
        eng.step()
    assert eng.ladder.stage == "shed"
    assert eng.compile_events - before == 0
    _assert_clean(eng)
    verify_at_escalation = None
    for ev in eng.sched.events:
        if ev == ("ladder", "despeculate"):
            verify_at_escalation = eng.metrics["verify_steps"]
    assert verify_at_escalation is not None
    # despeculated: chunks after the shrink stage pad to the smallest bucket
    assert eng.sched.prefill_chunk == min(eng.plugin.prefill_buckets)
    assert eng.sched.admission_reserve_pages > 0 and eng.sched.shed_armed
    for uid, p in enumerate(prompts):
        if uid in eng.results:
            assert eng.results[uid] == _ref_tokens(tiny_model, p, MAX_NEW), uid
    # relax all the way down restores every knob
    for _ in range(4):
        eng.ladder.relax()
    assert eng.ladder.stage == "normal"
    assert not eng.despeculated
    assert eng.sched.prefill_chunk == eng.plugin.prefill_chunk
    assert eng.sched.admission_reserve_pages == 0 and not eng.sched.shed_armed


def test_slo_monitor_drives_ladder(tiny_model):
    """SLO trips escalate the ladder one stage; recovery relaxes it — the
    transition-edge contract (a sustained breach is ONE escalation)."""
    eng = _engine(tiny_model)
    paged = []  # the operator's own alerting must keep firing after attach
    mon = SLOMonitor({"token_latency_s": {"p50_trip": 0.5}},
                     on_trip=lambda m, q, v: paged.append(m))
    eng.attach_slo(mon)
    for _ in range(8):
        mon.observe("token_latency_s", 2.0)  # breach: fires once, on the edge
    assert eng.ladder.stage == "despeculate"
    assert mon.trip_count == 1
    assert paged == ["token_latency_s"]  # ladder chained, did not replace
    for _ in range(200):
        mon.observe("token_latency_s", 0.001)  # recover
    assert eng.ladder.stage == "normal"
    assert ("ladder", "despeculate") in eng.sched.events
    assert ("ladder", "normal") in eng.sched.events


# ---------------------------------------------------------------------------
# the invariant checker itself + report plumbing + knobs
# ---------------------------------------------------------------------------


def test_verify_invariants_detects_planted_violations(tiny_model):
    eng = _engine(tiny_model)
    eng.add_request(Request(uid=0, prompt=(5, 9, 3), max_new_tokens=2))
    while not eng.idle():
        eng.step()
    assert verify_serving_invariants(eng) == []
    eng.sched.free_pages -= 1  # planted mirror drift
    problems = verify_serving_invariants(eng)
    assert any("mirror" in p for p in problems)
    assert any("conservation" in p for p in problems)
    eng.sched.free_pages += 1
    eng.sched.free_slots.pop()  # planted slot-accounting hole
    assert any("slot accounting" in p for p in verify_serving_invariants(eng))


def test_replay_emits_overload_fields_and_clean_twins(tiny_model):
    """The always-emitted overload block: zeros + goodput 1.0 on a clean
    replay, with the ``serving.*`` twin rows recorded against the clean-run
    model (status ok) — and ``verify_invariants=True`` passes."""
    trace = synthesize_trace(17, 6, vocab_size=255,
                             prompt_len_range=(3, 8), new_tokens_range=(2, 6))
    rep = replay(_engine(tiny_model), trace, verify_invariants=True)
    for field in ("requests_shed", "deadline_misses", "cancelled",
                  "pages_reclaimed_on_cancel", "request_goodput_frac",
                  "transfer_retries", "ladder_stage", "ladder_engagements"):
        assert field in rep, field
    assert rep["requests_shed"] == rep["cancelled"] == 0
    assert rep["deadline_misses"] == rep["pages_reclaimed_on_cancel"] == 0
    assert rep["request_goodput_frac"] == 1.0
    assert rep["transfer_retries"] == 0
    assert rep["ladder_stage"] == "normal"
    reg = twin_registry()
    for name in ("serving.requests_shed", "serving.deadline_misses",
                 "serving.cancelled", "serving.pages_reclaimed_on_cancel",
                 "serving.request_goodput_frac"):
        twin = reg.get(name)
        assert twin is not None and twin.status == "ok", (name, twin)


def test_adapter_transfer_retry_bounded_and_surfaced(tiny_model):
    """Satellite: an injected transfer failure mid-swap (or a memmap read
    blip) is absorbed by the bounded retry budget — the swap lands, the
    retry is counted into ``StreamStats.transfer_retries`` and surfaced in
    the replay report — while a failure past the budget still propagates
    loudly."""
    import tempfile

    from accelerate_tpu.resilience import TransientIOError
    from accelerate_tpu.serving import AdapterStore
    from accelerate_tpu.utils.dataclasses import LoraPlugin

    model, params = tiny_model
    lp = LoraPlugin(rank=2, pool_slots=2, kernel="native")
    with tempfile.TemporaryDirectory() as d:
        store = AdapterStore(params, lp, dtype=model.config.dtype, offload_dir=d)
        store.publish_random(1, jax.random.PRNGKey(101))
        store.publish_random(2, jax.random.PRNGKey(102))
        # H2D staging blip mid-prefetch: one retry, swap succeeds
        with fault_plan(FaultPlan([FaultEvent("transfer", at=1,
                                              site="adapter_transfer")])):
            slot, swapped = store.pin(1)
        assert swapped and store.stats.transfer_retries == 1
        # memmap-read blip: its own retry wrapper absorbs it
        with fault_plan(FaultPlan([FaultEvent("transfer", at=1,
                                              site="adapter_memmap")])):
            _, swapped = store.pin(2)
        assert swapped and store.stats.transfer_retries == 2
        # past the budget (count > retries): the failure propagates
        store3 = AdapterStore(params, lp, dtype=model.config.dtype,
                              offload_dir=d)
        store3.publish_random(3, jax.random.PRNGKey(103))
        with fault_plan(FaultPlan([FaultEvent("transfer", at=1, count=10,
                                              site="adapter_transfer")])):
            with pytest.raises(TransientIOError):
                store3.pin(3)

        # surfaced in the replay report: a tiny multi-tenant replay under
        # one injected mid-swap blip reports the absorbed retry
        store4 = AdapterStore(params, lp, dtype=model.config.dtype,
                              offload_dir=d)
        store4.publish_random(4, jax.random.PRNGKey(104))
        eng = ServingEngine(model, params, _plugin(),
                            GenerationConfig(max_new_tokens=MAX_NEW),
                            adapters=store4)
        trace = [Request(uid=0, prompt=(7, 11, 13), max_new_tokens=3,
                         adapter_id=4),
                 Request(uid=1, prompt=(5, 3), max_new_tokens=3)]
        with fault_plan(FaultPlan([FaultEvent("transfer", at=1,
                                              site="adapter_transfer")])):
            rep = replay(eng, trace, verify_invariants=True)
        assert rep["transfer_retries"] >= 1
        assert rep["completed"] == 2


def test_serving_plugin_overload_knobs(monkeypatch):
    monkeypatch.setenv("ACCELERATE_SERVE_MAX_QUEUE", "7")
    monkeypatch.setenv("ACCELERATE_SERVE_KV_WATERMARK", "0.8")
    monkeypatch.setenv("ACCELERATE_SERVE_DEADLINE", "64")
    p = ServingPlugin()
    assert (p.max_queue, p.kv_shed_watermark, p.default_deadline_ticks) == \
        (7, 0.8, 64)
    assert ServingPlugin(max_queue=3).max_queue == 3  # explicit args win
    with pytest.raises(ValueError):
        ServingPlugin(max_queue=-1)
    with pytest.raises(ValueError):
        ServingPlugin(kv_shed_watermark=1.5)
    with pytest.raises(ValueError):
        ServingPlugin(default_deadline_ticks=-2)
    with pytest.raises(ValueError):
        ServingPlugin(ladder_reserve_frac=0.0)


def test_default_deadline_stamped_on_submit(tiny_model):
    eng = _engine(tiny_model, default_deadline_ticks=5)
    eng.add_request(Request(uid=0, prompt=(4, 4), max_new_tokens=2))
    assert eng.sched.waiting[0].deadline_ticks == 5
    eng.add_request(Request(uid=1, prompt=(4, 4), max_new_tokens=2,
                            deadline_ticks=9))  # explicit wins
    assert eng.sched.waiting[1].deadline_ticks == 9
    with pytest.raises(ValueError):
        eng.add_request(Request(uid=2, prompt=(4, 4), max_new_tokens=2,
                                deadline_ticks=-1))
