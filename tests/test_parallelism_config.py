"""Mesh construction tests (mirror of reference tests/test_parallelism_config)."""

import jax
import pytest

from accelerate_tpu.parallelism_config import MESH_AXIS_ORDER, ParallelismConfig
from accelerate_tpu.utils.environment import patch_environment


def test_default_single():
    cfg = ParallelismConfig()
    assert cfg.total_size == 1


def test_dp_shard_mesh():
    cfg = ParallelismConfig(dp_shard_size=8)
    mesh = cfg.build_device_mesh()
    assert mesh.shape["dp_shard"] == 8
    assert all(mesh.shape[ax] == 1 for ax in MESH_AXIS_ORDER if ax != "dp_shard")


def test_2d_mesh():
    cfg = ParallelismConfig(dp_shard_size=4, tp_size=2)
    mesh = cfg.build_device_mesh()
    assert mesh.shape["dp_shard"] == 4
    assert mesh.shape["tp"] == 2


def test_hsdp_mesh():
    cfg = ParallelismConfig(dp_replicate_size=2, dp_shard_size=4)
    mesh = cfg.build_device_mesh()
    assert mesh.shape["dp_replicate"] == 2
    assert mesh.shape["dp_shard"] == 4
    assert cfg.dp_dim_names == ("dp_replicate", "dp_shard")


def test_infer_dp_shard():
    cfg = ParallelismConfig(dp_shard_size=-1, tp_size=2)
    mesh = cfg.build_device_mesh()
    assert cfg.dp_shard_size == 4
    assert mesh.shape["dp_shard"] == 4


def test_size_mismatch_raises():
    cfg = ParallelismConfig(dp_shard_size=3)
    with pytest.raises(ValueError):
        cfg.build_device_mesh()


def test_cp_sp_mutually_exclusive():
    cfg = ParallelismConfig(cp_size=2, sp_size=2, dp_shard_size=2)
    with pytest.raises(ValueError):
        cfg.build_device_mesh()


def test_joint_dims():
    cfg = ParallelismConfig(dp_shard_size=2, cp_size=2, tp_size=2)
    assert cfg.dp_shard_cp_dim_names == ("dp_shard", "cp")
    assert cfg.dp_cp_dim_names == ("dp_shard", "cp")
    assert cfg.fsdp_dim_names == ("dp_shard", "cp")
    assert cfg.seq_dim_names == ("cp",)
    assert cfg.non_data_parallel_size == 4
    assert cfg.data_parallel_size == 2


def test_env_roundtrip():
    cfg = ParallelismConfig(dp_replicate_size=2, dp_shard_size=2, tp_size=2)
    with patch_environment(**cfg.to_env()):
        cfg2 = ParallelismConfig.from_env()
    assert cfg2.dp_replicate_size == 2
    assert cfg2.dp_shard_size == 2
    assert cfg2.tp_size == 2
    assert cfg2.cp_size == 1


def test_batch_spec():
    from jax.sharding import PartitionSpec as P

    cfg = ParallelismConfig(dp_shard_size=4, cp_size=2)
    spec = cfg.batch_spec(seq_axis=1, ndim=3)
    assert spec == P(("dp_shard",), ("cp",), None)


def test_mesh_canonical_order():
    cfg = ParallelismConfig(dp_shard_size=8)
    mesh = cfg.build_device_mesh()
    assert tuple(mesh.axis_names) == MESH_AXIS_ORDER


def test_dcn_axis_outermost_and_transport():
    """The explicit cross-slice axis: outermost in the canonical order so
    slice boundaries land on the slowest network tier, included in every
    data-parallel dim group, and riding the PARALLELISM_CONFIG_* env
    transport like every other axis."""
    import os

    assert MESH_AXIS_ORDER[0] == "dcn"
    cfg = ParallelismConfig(dcn_size=2, dp_shard_size=4)
    assert cfg.has_dcn and cfg.data_parallel_size == 8
    mesh = cfg.build_device_mesh()
    assert mesh.shape["dcn"] == 2 and mesh.shape["dp_shard"] == 4
    assert cfg.dp_dim_names == ("dcn", "dp_shard")
    assert cfg.batch_dim_names == ("dcn", "dp_shard")
    assert cfg.dp_cp_dim_names == ("dcn", "dp_shard")
    # params replicate across slices: dcn is never an FSDP shard axis
    assert "dcn" not in cfg.fsdp_dim_names

    env = cfg.to_env()
    assert env["PARALLELISM_CONFIG_DCN_SIZE"] == "2"
    old = dict(os.environ)
    try:
        os.environ.update(env)
        rt = ParallelismConfig.from_env()
        assert rt.dcn_size == 2 and rt.dp_shard_size == 4
    finally:
        os.environ.clear()
        os.environ.update(old)


def test_dcn_dp_shard_inference_accounts_for_slices():
    cfg = ParallelismConfig(dcn_size=2, dp_shard_size=-1)
    cfg._validate(8)
    assert cfg.dp_shard_size == 4
