"""Driver-entry legs exercised as unit tests on the 8-device CPU mesh.

``dryrun_multichip`` itself is run by the driver; these tests pin the two
round-3 legs (composed dp×tp×pp multi-step training with save/restore, and
the sharded over-HBM checkpoint-to-decode path) so a regression shows up in
the suite before the driver artifact."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


@pytest.mark.slow
def test_composed_dp_tp_pp_leg():
    losses_and_cont, restore_ok = graft._composed_dp_tp_pp_leg(
        8, np.random.default_rng(0)
    )
    assert restore_ok
    losses = losses_and_cont[:3]
    assert all(np.isfinite(losses))
    assert losses[2] < losses[1] < losses[0]


@pytest.mark.slow
def test_sharded_over_hbm_decode_leg():
    info = graft._sharded_over_hbm_decode_leg(8, np.random.default_rng(0))
    assert "tokens ok" in info
    assert "tp" in info  # params actually tp-sharded


@pytest.mark.slow
def test_resilience_leg():
    info = graft._resilience_leg(np.random.default_rng(0))
    assert "parity ok" in info
    assert "exit75" in info and "fallback" in info


@pytest.mark.slow
def test_plan_infer_report_70b():
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from bench import plan_infer_report

    rep = plan_infer_report(16, seq=2048, batch=8)
    # the whole model is many chips' worth of weights...
    assert rep["chips_worth_of_weights"] > 4
    # ...but each device's slice (+ kv cache + workspace) fits a v5e
    assert rep["fits_v5e_16GiB"]
    assert rep["per_device_GiB"]["total_hbm"] < 15
    # sanity: tp capped at the GQA kv-head count
    assert rep["mesh"]["tp"] == 8


@pytest.mark.slow
def test_launch_leg():
    """The multi-host launch story across REAL process boundaries: 2-proc
    bitwise loss parity vs the single-process mesh, SIGTERM on rank 1 →
    agreed stop → exit 75 → `launch --resume` onto 1 process with exact
    continuation parity (hierarchical ICI→DCN sync engaged throughout)."""
    info = graft._launch_leg()
    assert "bitwise parity ok" in info
    assert "resume@1proc" in info and "exact" in info


@pytest.mark.slow
def test_telemetry_leg():
    info = graft._telemetry_leg(np.random.default_rng(0))
    assert "tokens bitwise" in info and "schema valid" in info


@pytest.mark.slow
def test_prefix_leg():
    """tp=2 prefix-cached serve over shared-system-prompt traffic: hit
    rate > 0 with the scheduler-replay twin in exact agreement, survivors
    bitwise vs the reuse-off replay, zero post-warmup compiles, refcounted
    invariants green (the leg itself raises on any of these failing)."""
    info = graft._prefix_leg(np.random.default_rng(0))
    assert "parity ok" in info and "compiles=0" in info
    assert "hit_rate=" in info and "tp" in info


@pytest.mark.slow
def test_speculate_leg():
    """tp=2 speculative serve: token parity vs generate() over the same
    TP-sharded params, strict_compiles post-warmup, and a real tokens/step
    win (the leg itself raises on any of these failing)."""
    info = graft._speculate_leg(np.random.default_rng(0))
    assert "parity ok" in info and "compiles=0" in info
    assert "tp" in info  # params actually tp-sharded
