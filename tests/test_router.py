"""Fleet router tests: prefix-/adapter-affinity routing over N replicas,
drain/respawn on ``replica_kill``, fleet twins, and the multi-host fabric
leg (ISSUE 19).

The acceptance pins: a routed fleet's tokens are BITWISE identical to a
single fused engine serving the same trace (prefix reuse + adapters +
speculation all armed), zero post-warmup compiles on every replica
(``fleet_replay`` raises otherwise), prefix-affinity routing beats
round-robin on BOTH fleet prefix hit rate and p50 TTFT ticks on the
seeded shared-preamble trace, and a ``replica_kill`` mid-traffic drains
the victim through the survivors contract — pending work re-routes
exactly once, surviving tokens stay bitwise equal to the fault-free
fleet replay, and the fleet prefix twin counts each request's offered
traffic exactly once across the re-route.

Every engine in this module shares test_prefix_cache.py's geometry
(slots=4, page=4, pool=24, chunk=8) so the process-shared jit cache
compiles each program exactly once across the serving modules (the
tier-1 time budget).
"""

import jax
import jax.numpy as jnp
import pytest

from accelerate_tpu.generation import GenerationConfig
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from accelerate_tpu.resilience import FaultEvent, FaultPlan
from accelerate_tpu.serving import (
    AdapterStore,
    DisaggregatedPair,
    FleetRouter,
    ServingEngine,
    fleet_chaos_replay,
    fleet_replay,
    replay,
    synthesize_trace,
)
from accelerate_tpu.telemetry import SLOMonitor, twin_registry
from accelerate_tpu.utils.dataclasses import LoraPlugin, ServingPlugin

MAX_NEW = 16  # ONE decode budget for the module: every engine shares jits


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return model, params


def _plugin(**kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 8)
    kw.setdefault("num_pages", 24)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("decode_kernel", "native")
    return ServingPlugin(**kw)


def _gen():
    return GenerationConfig(max_new_tokens=MAX_NEW)


def _engine(tiny_model, store=None, **kw):
    model, params = tiny_model
    return ServingEngine(model, params, _plugin(**kw), _gen(), adapters=store)


def _engine_fleet(tiny_model, n=2, policy="affinity", **kw):
    kw.setdefault("prefix_cache", "on")
    return FleetRouter([_engine(tiny_model, **kw) for _ in range(n)],
                       policy=policy)


def _store(tiny_model, n_tenants=2):
    """A pool store with the SAME seeded adapter trees every call — a
    fleet shares the tenant registry, each replica keeps its own pool."""
    _, params = tiny_model
    s = AdapterStore(params, LoraPlugin(rank=2, pool_slots=2),
                     dtype=jnp.float32)
    for t in range(1, n_tenants + 1):
        s.publish_random(t, jax.random.PRNGKey(1000 + t))
    return s


def _shared_trace(seed, n, share=0.9, groups=2, pre_len=12, inter=1.0):
    return synthesize_trace(
        seed, n, vocab_size=256, mean_interarrival_steps=inter,
        prompt_len_range=(4, 12), new_tokens_range=(4, 8),
        prefix_share=share, shared_prefixes=groups, shared_prefix_len=pre_len,
    )


# ---------------------------------------------------------------------------
# construction + policy validation
# ---------------------------------------------------------------------------


def test_router_rejects_empty_fleet_and_unknown_policy(tiny_model):
    with pytest.raises(ValueError, match="at least one replica"):
        FleetRouter([])
    with pytest.raises(ValueError, match="routing policy"):
        FleetRouter([_engine(tiny_model)], policy="random")


def test_replica_kill_is_a_registered_fault_kind():
    """``replica_kill`` validates as a fault kind; a typo still raises."""
    FaultEvent("replica_kill", at=3)  # must not raise
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultEvent("replica_smite", at=3)


# ---------------------------------------------------------------------------
# the fleet parity pin
# ---------------------------------------------------------------------------


def test_engine_fleet_parity_vs_fused(tiny_model):
    """2 fused-engine replicas behind affinity routing: merged tokens are
    BITWISE identical to ONE engine serving the same trace, goodput 1.0,
    zero post-warmup compiles on every replica, and the shared-preamble
    trace actually routes by prefix."""
    trace = _shared_trace(3, 10)
    router = _engine_fleet(tiny_model)
    rep = fleet_replay(router, trace)
    fused = replay(_engine(tiny_model, prefix_cache="on"), trace)
    assert rep["results"] == fused["results"]
    assert rep["goodput_frac"] == 1.0
    assert rep["completed"] == len(trace)
    assert rep["compiles_measured"] == 0
    assert rep["routed_by_prefix"] > 0
    assert rep["alive"] == rep["replicas"] == 2
    assert len(rep["per_replica"]) == 2
    assert all(row["routed"] > 0 for row in rep["per_replica"])


def test_pair_fleet_parity_all_armed(tiny_model):
    """The full fleet parity pin: 2 disaggregated prefill→decode pairs
    (prefix reuse + multi-tenant adapters + speculative decode all armed,
    one AdapterStore per role per replica) behind the router — tokens
    BITWISE equal to one fused speculative engine with the same adapters,
    KV pages crossed the wire (bytes > 0), zero post-warmup compiles, and
    the warmup sweep reports compiles per role."""
    model, params = tiny_model
    trace = synthesize_trace(
        23, 12, vocab_size=256, mean_interarrival_steps=1.0,
        prompt_len_range=(4, 12), new_tokens_range=(4, 8),
        adapters=2, prefix_share=0.6, shared_prefix_len=8,
    )

    def pair():
        return DisaggregatedPair(
            model, params,
            _plugin(prefix_cache="on", speculate="ngram", speculate_k=2),
            _gen(), adapters=_store(tiny_model),
            prefill_adapters=_store(tiny_model),
        )

    router = FleetRouter([pair(), pair()])
    rep = fleet_replay(router, trace)
    fused = replay(
        _engine(tiny_model, store=_store(tiny_model), prefix_cache="on",
                speculate="ngram", speculate_k=2),
        trace,
    )
    assert rep["results"] == fused["results"]
    assert rep["goodput_frac"] == 1.0
    assert rep["compiles_measured"] == 0
    assert rep["page_transfer_bytes"] > 0
    assert rep["adapter_pool_hit_rate"] > 0
    roles = rep["compiles_warmup_by_role"]
    assert set(roles) >= {"prefill", "decode"}, roles
    assert all(row["role"] == "pair" for row in rep["per_replica"])


# ---------------------------------------------------------------------------
# the perf pin: prefix affinity beats round-robin
# ---------------------------------------------------------------------------


def test_prefix_affinity_beats_round_robin(tiny_model):
    """The routing win, CPU-measurable and deterministic: on a loaded
    4-preamble trace (more hot preambles than one replica's cache can
    keep resident) affinity routing converges each preamble class onto a
    home replica while round-robin scatters them — affinity must beat
    round-robin on BOTH the fleet prefix hit rate and p50 TTFT (virtual
    ticks, the deterministic clock)."""
    trace = _shared_trace(3, 24, share=0.95, groups=4, inter=0.5)
    by_policy = {}
    for policy in ("affinity", "round_robin"):
        rep = fleet_replay(_engine_fleet(tiny_model, policy=policy), trace)
        assert rep["goodput_frac"] == 1.0
        assert rep["compiles_measured"] == 0
        by_policy[policy] = rep
    aff, rr = by_policy["affinity"], by_policy["round_robin"]
    assert rr["routed_by_prefix"] == 0  # round-robin never routes by content
    assert aff["routed_by_prefix"] > len(trace) // 2
    assert aff["prefix_hit_rate"] > rr["prefix_hit_rate"], (
        aff["prefix_hit_rate"], rr["prefix_hit_rate"])
    assert aff["ttft_p50_ticks"] < rr["ttft_p50_ticks"], (
        aff["ttft_p50_ticks"], rr["ttft_p50_ticks"])
    # both policies keep token parity with each other — routing moves
    # WHERE a request decodes, never what it says
    assert aff["results"] == rr["results"]


def test_adapter_affinity_keeps_tenants_home(tiny_model):
    """A tenant sticks to replicas holding its adapter resident: after the
    first placement pins the weights, later same-tenant arrivals route by
    adapter affinity instead of scattering (the S-LoRA discipline)."""
    trace = synthesize_trace(
        7, 12, vocab_size=256, mean_interarrival_steps=1.0,
        prompt_len_range=(4, 12), new_tokens_range=(4, 8), adapters=2,
    )
    router = FleetRouter([
        _engine(tiny_model, store=_store(tiny_model)) for _ in range(2)
    ])
    rep = fleet_replay(router, trace)
    assert rep["goodput_frac"] == 1.0
    assert rep["routed_by_adapter"] > 0
    assert rep["adapter_pool_hit_rate"] > 0


# ---------------------------------------------------------------------------
# replica_kill: drain, re-route, respawn
# ---------------------------------------------------------------------------


def test_replica_kill_drain_reroute_bitwise_parity(tiny_model):
    """The chaos pin: a ``replica_kill`` mid-traffic drains the victim
    (completed work stays completed), re-routes every pending request
    exactly once, and the surviving tokens are BITWISE identical to the
    fault-free fleet replay — with zero post-warmup compiles across the
    drain."""
    trace = _shared_trace(5, 12, inter=1.0)
    rep = fleet_chaos_replay(
        lambda: _engine_fleet(tiny_model), trace,
        FaultPlan([FaultEvent("replica_kill", at=8)]),
    )
    assert rep["token_parity"] is True
    assert rep["goodput_frac"] == 1.0
    assert rep["completed"] == len(trace)
    assert rep["faults_fired"] == 1
    assert len(rep["drain_events"]) == 1
    assert rep["drain_events"][0]["survivors"] > 0
    assert rep["compiles_measured"] == 0
    assert rep["alive"] == 1


def test_drain_counts_offered_traffic_once(tiny_model):
    """The fleet prefix twin's once-only contract: a drained request's
    cacheable preamble was already counted as offered traffic on the
    victim, so the re-route target must NOT count it again — the fleet's
    total offered pages match the fault-free fleet's exactly."""

    def offered(router):
        return sum(
            eng.prefix.stats["admission_lookups"]
            for rep_ in router.replicas for eng in rep_.engines
            if eng.prefix is not None
        )

    trace = _shared_trace(5, 12, inter=1.0)
    clean = _engine_fleet(tiny_model)
    fleet_replay(clean, trace)
    chaos = _engine_fleet(tiny_model)
    from accelerate_tpu.resilience import fault_plan

    chaos.warmup()
    with fault_plan(FaultPlan([FaultEvent("replica_kill", at=8)])):
        chaos.run(trace)
    assert len(chaos.drain_events) == 1
    assert chaos.drain_events[0]["survivors"] > 0
    assert offered(chaos) == offered(clean), (
        "a drained request's preamble was double-counted across the "
        "re-route")


def test_respawn_restores_fleet_capacity(tiny_model):
    """With a respawn factory the drain appends a fresh warmed replica:
    capacity recovers, the fresh replica takes traffic, strict_compiles
    still holds (the respawn warms before admitting)."""
    trace = _shared_trace(9, 12, inter=0.5)
    router = FleetRouter(
        [_engine(tiny_model, prefix_cache="on") for _ in range(2)],
        respawn=lambda i: _engine(tiny_model, prefix_cache="on"),
    )
    with_respawn = fleet_chaos_replay(
        lambda: router, trace,
        FaultPlan([FaultEvent("replica_kill", at=6)]),
        baseline_parity=False,
    )
    assert with_respawn["goodput_frac"] == 1.0
    assert with_respawn["replicas"] == 3      # victim kept + fresh appended
    assert with_respawn["alive"] == 2
    assert with_respawn["compiles_measured"] == 0


def test_single_replica_fleet_ignores_kill(tiny_model):
    """A 1-replica fleet with no respawn has nowhere to re-route: the kill
    is ignored and every request still completes."""
    trace = _shared_trace(11, 6, inter=1.0)
    rep = fleet_chaos_replay(
        lambda: _engine_fleet(tiny_model, n=1), trace,
        FaultPlan([FaultEvent("replica_kill", at=5)]),
    )
    assert rep["goodput_frac"] == 1.0
    assert rep["drain_events"] == []
    assert rep["alive"] == 1


# ---------------------------------------------------------------------------
# fleet-wide degradation + twins + prewarm
# ---------------------------------------------------------------------------


def test_fleet_ladder_escalates_in_lockstep(tiny_model):
    """One breached SLO escalates EVERY alive replica's ladder one stage
    (and recovery relaxes them all) — the fleet moves like one engine,
    and callbacks the monitor already carried keep firing."""
    router = _engine_fleet(tiny_model)
    paged = []
    mon = SLOMonitor({"token_latency_s": {"p50_trip": 0.5}},
                     on_trip=lambda m, q, v: paged.append(m))
    router.attach(mon)
    for _ in range(8):
        mon.observe("token_latency_s", 2.0)
    for rep_ in router.replicas:
        for eng in rep_.engines:
            assert eng.ladder.stage == "despeculate"
    assert paged == ["token_latency_s"]  # chained, not replaced
    for _ in range(200):
        mon.observe("token_latency_s", 0.001)
    for rep_ in router.replicas:
        for eng in rep_.engines:
            assert eng.ladder.stage == "normal"


def test_fleet_twins_recorded_and_zeros_clean(tiny_model):
    """``fleet_replay`` records the fleet twin rows: request_goodput
    measured 1.0 against the clean-run prediction 1.0 (status ok), the
    hit-rate twins carry measured + predicted sides; an EMPTY trace keeps
    every report field present and zeroed (the always-emitted
    contract)."""
    rep = fleet_replay(_engine_fleet(tiny_model), _shared_trace(13, 8))
    assert rep["goodput_frac"] == 1.0
    reg = twin_registry()
    good = reg.get("fleet.request_goodput")
    assert good is not None and good.status == "ok", good
    assert good.measured == good.predicted == 1.0
    prefix_twin = reg.get("fleet.prefix_hit_rate")
    assert prefix_twin is not None
    assert prefix_twin.measured == pytest.approx(rep["prefix_hit_rate"])
    assert prefix_twin.predicted is not None

    idle = fleet_replay(_engine_fleet(tiny_model), [])
    assert idle["requests"] == idle["completed"] == 0
    assert idle["goodput_frac"] == 0.0
    assert idle["ttft_p50_ticks"] == 0.0
    assert idle["prefix_hit_rate"] == 0.0
    assert idle["adapter_pool_hit_rate"] == 0.0
    assert idle["page_transfer_bytes"] == 0
    assert idle["compiles_measured"] == 0
    assert idle["drain_events"] == []


def test_fleet_prewarm_pack_shared_across_replicas(tiny_model, tmp_path):
    """``warmup(prewarm_dir=...)`` packs one ``export_prewarm`` tar per
    role; a later fleet pointed at the same directory loads it before
    warming (the cross-process compile-cache hand-off the fabric leg
    exercises for real)."""
    router = FleetRouter([_engine(tiny_model, prefix_cache="on")
                          for _ in range(2)])
    by_role = router.warmup(prewarm_dir=str(tmp_path))
    assert (tmp_path / "prewarm-engine.tar").exists()
    assert set(by_role) == {"engine"}
    again = FleetRouter([_engine(tiny_model, prefix_cache="on")])
    again.warmup(prewarm_dir=str(tmp_path))  # loads, must not raise
    assert again.compiles_measured() == {0: 0}


# ---------------------------------------------------------------------------
# the multi-host fabric leg (slow: real process boundaries)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_fabric_two_process_launch(tmp_path):
    """The fabric across REAL process boundaries: rank 0 (prefill role)
    streams finished KV pages — int8 codes + fp32 amax scales — to rank 1
    (decode role, speculation armed) over the dcn broadcast plumbing.
    Pins: bitwise token parity vs a fused serve, bytes sent == received ==
    the dcn byte model (tolerance 0), ZERO post-warmup compiles per role,
    one prewarm pack exported per role, and the on-rank fleet-router
    smoke."""
    import json
    import os
    import subprocess
    import sys

    from accelerate_tpu.test_utils import fleet_fabric_script_path

    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("ACCELERATE_", "PARALLELISM_CONFIG_",
                                "FSDP_"))}
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p)
    env["FLEET_LEG_DIR"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.launch", "--cpu",
         "--num_processes", "2", "--num_cpu_devices", "1",
         str(fleet_fabric_script_path())],
        capture_output=True, text=True, timeout=560, env=env,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    payload = json.loads(
        [l for l in r.stdout.splitlines() if l.startswith("{")][-1])
    assert payload["parity"] is True
    assert payload["bytes_sent"] == payload["bytes_recv"] \
        == payload["bytes_pred"] > 0
    assert payload["compiles_prefill"] == payload["compiles_decode"] == 0
    assert (tmp_path / "prewarm-prefill.tar").exists()
    assert (tmp_path / "prewarm-decode.tar").exists()
    smoke = payload["fleet"]
    assert smoke["goodput_frac"] == 1.0
    assert smoke["routed_by_prefix"] > 0
    assert smoke["compiles_measured"] == 0
