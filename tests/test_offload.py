"""Host-offload training tests (ZeRO-offload analog — reference DeepSpeed
``offload_optimizer_device``/``offload_param_device`` dataclasses.py:1172-1187
and FSDP CPUOffload).

On the CPU test mesh, memory-kind placement is unsupported so storage stays
in device memory, but the host-compute update region (``compute_on``) — the
code path that runs on TPU — is fully exercised, and numerics are pinned
offload-vs-resident.  The real pinned-host placement is asserted on-chip by
``bench.py --offload``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ParallelismConfig
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.test_utils.training import make_regression_loader, regression_loss_fn
from accelerate_tpu.utils.dataclasses import (
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
)


def _mlp_params(seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "dense": {"kernel": jax.random.normal(k1, (8, 64)) * 0.1, "bias": jnp.zeros((64,))},
        "out": {"kernel": jax.random.normal(k2, (64, 1)) * 0.1, "bias": jnp.zeros((1,))},
    }


def _mlp_loss(params, batch):
    h = jnp.tanh(batch["x"] @ params["dense"]["kernel"] + params["dense"]["bias"])
    pred = (h @ params["out"]["kernel"] + params["out"]["bias"])[..., 0]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batches(n=6, bs=16, seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(bs, 8)).astype(np.float32)
        y = (x.sum(-1) * 0.5).astype(np.float32)
        out.append({"x": jnp.asarray(x), "y": jnp.asarray(y)})
    return out


def _run(offload: bool, accum_plugin=None, mixed_precision="no", n_steps=6,
         chunk_gib=None, tx=None, max_grad_norm=1.0, kwargs_handlers=None,
         pipeline=True):
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    plugin = FullyShardedDataParallelPlugin(
        min_weight_size=0, cpu_offload=offload, host_update_chunk_gib=chunk_gib,
        host_update_pipeline=pipeline,
    )
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=plugin,
        gradient_accumulation_plugin=accum_plugin,
        mixed_precision=mixed_precision,
        kwargs_handlers=kwargs_handlers,
    )
    tx = acc.prepare(tx if tx is not None else optax.adamw(1e-2))
    state = acc.create_train_state(_mlp_params(), tx)
    step = acc.prepare_train_step(_mlp_loss, max_grad_norm=max_grad_norm)
    losses = []
    for batch in _batches(n=n_steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    params = jax.device_get(state.params)
    return losses, params


def test_offload_matches_resident_simple():
    """Host-compute adamw update == resident update, bit-for-bit on CPU."""
    losses_res, params_res = _run(offload=False)
    losses_off, params_off = _run(offload=True)
    np.testing.assert_allclose(losses_off, losses_res, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), params_off, params_res
    )


@pytest.mark.slow
def test_offload_matches_resident_across_steps_accum():
    """compute_on inside the lax.cond update boundary (across_steps mode)."""
    plugin = GradientAccumulationPlugin(num_steps=3, mode="across_steps")
    losses_res, params_res = _run(offload=False, accum_plugin=plugin)
    losses_off, params_off = _run(offload=True, accum_plugin=plugin)
    np.testing.assert_allclose(losses_off, losses_res, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), params_off, params_res
    )


def test_offload_with_bf16_grads_tracks_resident():
    """The 7B bench recipe: cpu_offload + GradSyncKwargs(grad_dtype='bf16')
    (grads born compute-width, host upcasts inside the update region) must
    track the resident fp32-grad run."""
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    losses_res, params_res = _run(offload=False, mixed_precision="bf16",
                                  max_grad_norm=None)
    losses_off, params_off = _run(
        offload=True, mixed_precision="bf16", max_grad_norm=None,
        kwargs_handlers=[GradSyncKwargs(grad_dtype="bf16")],
    )
    # bf16 grads differ from fp32 grads in the last bits; the trajectories
    # must stay close, not bitwise-equal
    np.testing.assert_allclose(losses_off, losses_res, rtol=5e-2)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=0.1, atol=5e-3
        ),
        params_off, params_res,
    )


def test_offload_matches_resident_in_step_accum():
    """compute_on after the scan accumulation (in_step mode)."""
    plugin = GradientAccumulationPlugin(num_steps=4, mode="in_step")
    losses_res, params_res = _run(offload=False, accum_plugin=plugin)
    losses_off, params_off = _run(offload=True, accum_plugin=plugin)
    np.testing.assert_allclose(losses_off, losses_res, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), params_off, params_res
    )


def test_chunked_host_update_matches_monolithic():
    """Per-leaf-group compute_on regions == one monolithic region, bit-exact
    (VERDICT r2 next #1 done-condition).  A tiny chunk budget forces one leaf
    per group (4 groups for the MLP), exercising slice/merge and the
    serialization tokens."""
    losses_mono, params_mono = _run(offload=True)
    losses_chunk, params_chunk = _run(offload=True, chunk_gib=1e-6)
    # ulp-level tolerance: the math is identical per leaf, but XLA fuses the
    # two graphs differently (fma boundaries), so exact bitwise equality is
    # not guaranteed
    np.testing.assert_allclose(losses_chunk, losses_mono, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-6, atol=1e-8),
        params_chunk, params_mono,
    )


def test_chunked_host_update_matches_resident():
    """Chunked offload == resident training (the full parity chain)."""
    losses_res, params_res = _run(offload=False)
    losses_chunk, params_chunk = _run(offload=True, chunk_gib=1e-6)
    np.testing.assert_allclose(losses_chunk, losses_res, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5), params_chunk, params_res
    )


@pytest.mark.slow
def test_chunked_host_update_with_accum_and_injected_hyperparams():
    """Chunking composes with in_step accumulation and the 7B bench's
    inject_hyperparams(lion) optimizer (traced scalars in the state tree)."""
    accum = GradientAccumulationPlugin(num_steps=2, mode="in_step")
    tx = optax.inject_hyperparams(optax.lion)(learning_rate=1e-2, b1=0.9, b2=0.99)
    losses_mono, params_mono = _run(offload=True, accum_plugin=accum, tx=tx)
    losses_chunk, params_chunk = _run(
        offload=True, accum_plugin=accum, tx=tx, chunk_gib=1e-6
    )
    np.testing.assert_allclose(losses_chunk, losses_mono, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-6, atol=1e-8),
        params_chunk, params_mono,
    )


@pytest.mark.slow
def test_chunked_host_update_unclipped():
    """max_grad_norm=None (the 7B configuration) under chunking."""
    losses_mono, params_mono = _run(offload=True, max_grad_norm=None)
    losses_chunk, params_chunk = _run(offload=True, chunk_gib=1e-6, max_grad_norm=None)
    np.testing.assert_allclose(losses_chunk, losses_mono, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-6, atol=1e-8),
        params_chunk, params_mono,
    )


@pytest.mark.slow
def test_offload_with_fp16_loss_scaling():
    """The overflow-hold wheres run inside the host region; training stays
    finite and converges under dynamic loss scaling."""
    losses, _ = _run(offload=True, mixed_precision="fp16", n_steps=8)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_offload_plugin_flag_resolution():
    p = FullyShardedDataParallelPlugin(cpu_offload=True)
    assert p.offload_params is True  # follows cpu_offload by default
    p2 = FullyShardedDataParallelPlugin(cpu_offload=True, offload_params=False)
    assert p2.offload_params is False
    p3 = FullyShardedDataParallelPlugin()
    assert p3.cpu_offload is False


@pytest.mark.slow
def test_offload_with_reference_accelerate_loop(  # the reference loop shape
):
    """Offload works through the plain prepare()/dataloader flow too."""
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0, cpu_offload=True),
    )
    dl = acc.prepare(make_regression_loader(batch_size=16))
    tx = acc.prepare(optax.adamw(0.05))
    state = acc.create_train_state({"a": jnp.zeros(()), "b": jnp.zeros(())}, tx)
    step = acc.prepare_train_step(regression_loss_fn)
    losses = []
    for _ in range(4):
        for batch in dl:
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_offload_state_checkpoint_roundtrip(tmp_path):
    """save_state/load_state round-trips an offload-configured TrainState and
    training continues (on TPU the restore also re-pins host-resident
    members to pinned_host — checkpointing.py _restore_placement; memory
    kinds degrade to device on the CPU mesh so this covers the flow)."""
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        project_dir=str(tmp_path),
        fsdp_plugin=FullyShardedDataParallelPlugin(min_weight_size=0, cpu_offload=True),
    )
    state = acc.create_train_state(_mlp_params(), acc.prepare(optax.adamw(1e-2)))
    step = acc.prepare_train_step(_mlp_loss)
    for batch in _batches(n=2):
        state, _ = step(state, batch)
    w_before = np.asarray(state.params["dense"]["kernel"])
    path = acc.save_state(train_state=state)
    zeroed = state.replace(params=jax.tree_util.tree_map(jnp.zeros_like, state.params))
    restored = acc.load_state(path, train_state=zeroed)
    np.testing.assert_allclose(np.asarray(restored.params["dense"]["kernel"]), w_before)
    restored, m = step(restored, _batches(n=1)[0])
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_offload_adafactor_matches_resident():
    """adafactor under the offload step == resident, on the CPU mesh (the
    compute_on region runs either way; real pinned-host placement is the
    on-chip concern test_host_constant_hoist covers abstractly)."""
    tx = optax.adafactor(1e-2)
    res, p_res = _run(False, tx=tx, max_grad_norm=None)
    off, p_off = _run(True, tx=tx, max_grad_norm=None)
    np.testing.assert_allclose(res, off, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), p_res, p_off)


def test_host_constant_hoist():
    """_host_constant_hoist surfaces jaxpr constant arrays as pinned args
    and preserves the function's outputs (adafactor-under-offload enabler).
    On CPU we pin to a plain sharding — the mechanism, not the memory kind."""
    from accelerate_tpu.accelerator import _host_constant_hoist

    const = jnp.arange(8, dtype=jnp.float32)  # captured array -> jaxpr const

    def fn(x, y):
        return jnp.where(x > 0, x * const, y), y + const.sum()

    x = jnp.asarray(np.random.default_rng(0).normal(size=(8,)), jnp.float32)
    y = jnp.ones((8,), jnp.float32)
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    hoisted = _host_constant_hoist(fn, sharding, x, y)
    assert hoisted is not fn  # the constant WAS hoisted
    for a, b in zip(fn(x, y), hoisted(x, y)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    def no_const(x, y):
        return x + y
    assert _host_constant_hoist(no_const, sharding, x, y) is no_const


def test_offload_lion_sr_bf16_masters_trains():
    """The lion-sr 7B recipe (ops/stochastic_rounding.py) through the full
    offload machinery on the CPU mesh: bf16 stored params (no fp32 master
    tree), SR update inside the host-compute region, monolithic and chunked.
    Offload == resident bitwise (deterministic SR keys); chunked differs
    only in key grouping, so it is asserted to train, not to match."""
    from accelerate_tpu.ops.stochastic_rounding import lion_bf16_sr
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    def run(offload, chunk_gib=None):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        plugin = FullyShardedDataParallelPlugin(
            min_weight_size=0, cpu_offload=offload, host_update_chunk_gib=chunk_gib
        )
        acc = Accelerator(
            parallelism_config=ParallelismConfig(dp_shard_size=8),
            fsdp_plugin=plugin, mixed_precision="bf16",
            kwargs_handlers=[GradSyncKwargs(grad_dtype="bf16")],
        )
        params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), _mlp_params()
        )
        state = acc.create_train_state(params, acc.prepare(lion_bf16_sr(3e-3)))
        step = acc.prepare_train_step(_mlp_loss, max_grad_norm=None)
        losses = []
        for batch in _batches(n=6):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses, jax.device_get(state.params)

    losses_res, params_res = run(False)
    losses_off, params_off = run(True)
    assert jax.tree_util.tree_leaves(params_res)[0].dtype == jnp.bfloat16
    np.testing.assert_allclose(losses_off, losses_res, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params_off, params_res
    )

    # quality: SR over bf16 masters must track plain lion over fp32 masters
    # at the SAME hyperparams (convergence itself is pinned at length in
    # tests/test_stochastic_rounding.py — 6 sign-steps on this landscape
    # need not decrease monotonically for either optimizer)
    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc_ref = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8),
                          mixed_precision="bf16")
    # weight_decay=0.0 explicitly: optax.lion's own default is 1e-3, the SR
    # recipe's is 0.0 — the reference must run the same hyperparameters
    ref_state = acc_ref.create_train_state(
        _mlp_params(), acc_ref.prepare(optax.lion(3e-3, b1=0.9, b2=0.99,
                                                  weight_decay=0.0,
                                                  mu_dtype=jnp.bfloat16)))
    ref_step = acc_ref.prepare_train_step(_mlp_loss, max_grad_norm=None)
    ref_losses = []
    for batch in _batches(n=6):
        ref_state, m = ref_step(ref_state, batch)
        ref_losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses_res, ref_losses, rtol=0.35)

    losses_chunk, params_chunk = run(True, chunk_gib=1e-6)  # every leaf its own group
    assert jax.tree_util.tree_leaves(params_chunk)[0].dtype == jnp.bfloat16
    assert np.isfinite(losses_chunk).all()
    np.testing.assert_allclose(losses_chunk, ref_losses, rtol=0.35)


def test_offload_adamw_sr_bf16_masters_trains():
    """adamw_bf16_sr (bf16 params + bf16 SR-maintained m/v) through the
    offload machinery: same contracts as the lion-sr test — offload ==
    resident bitwise (deterministic SR keys), chunked trains, and the SR
    recipe tracks fp32 adamw at the same hyperparams."""
    from accelerate_tpu.ops.stochastic_rounding import adamw_bf16_sr
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    def run(offload, chunk_gib=None):
        AcceleratorState._reset_state(reset_partial_state=True)
        GradientState._reset_state()
        plugin = FullyShardedDataParallelPlugin(
            min_weight_size=0, cpu_offload=offload, host_update_chunk_gib=chunk_gib
        )
        acc = Accelerator(
            parallelism_config=ParallelismConfig(dp_shard_size=8),
            fsdp_plugin=plugin, mixed_precision="bf16",
            kwargs_handlers=[GradSyncKwargs(grad_dtype="bf16")],
        )
        params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), _mlp_params()
        )
        state = acc.create_train_state(params, acc.prepare(adamw_bf16_sr(3e-3)))
        step = acc.prepare_train_step(_mlp_loss, max_grad_norm=None)
        losses = []
        for batch in _batches(n=6):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses, jax.device_get(state.params)

    losses_res, params_res = run(False)
    losses_off, params_off = run(True)
    assert jax.tree_util.tree_leaves(params_res)[0].dtype == jnp.bfloat16
    np.testing.assert_allclose(losses_off, losses_res, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params_off, params_res
    )

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    acc_ref = Accelerator(parallelism_config=ParallelismConfig(dp_shard_size=8),
                          mixed_precision="bf16")
    # weight_decay=0.0 explicitly: optax.adamw's own default is 1e-4, the SR
    # recipe's is 0.0 — the reference must run the same hyperparameters
    ref_state = acc_ref.create_train_state(
        _mlp_params(), acc_ref.prepare(optax.adamw(3e-3, weight_decay=0.0)))
    ref_step = acc_ref.prepare_train_step(_mlp_loss, max_grad_norm=None)
    ref_losses = []
    for batch in _batches(n=6):
        ref_state, m = ref_step(ref_state, batch)
        ref_losses.append(float(m["loss"]))
    np.testing.assert_allclose(losses_res, ref_losses, rtol=0.35)

    losses_chunk, params_chunk = run(True, chunk_gib=1e-6)
    assert jax.tree_util.tree_leaves(params_chunk)[0].dtype == jnp.bfloat16
    assert np.isfinite(losses_chunk).all()
    np.testing.assert_allclose(losses_chunk, ref_losses, rtol=0.35)


def _run_sr8(recipe, offload, chunk_gib=None, pipeline=True):
    """The -sr8 recipes (ops/int8_state.py: bf16 SR params + int8 blockwise
    moment state) through the full offload machinery on the CPU mesh."""
    from accelerate_tpu.utils.dataclasses import GradSyncKwargs

    AcceleratorState._reset_state(reset_partial_state=True)
    GradientState._reset_state()
    plugin = FullyShardedDataParallelPlugin(
        min_weight_size=0, cpu_offload=offload, host_update_chunk_gib=chunk_gib,
        host_update_pipeline=pipeline,
    )
    acc = Accelerator(
        parallelism_config=ParallelismConfig(dp_shard_size=8),
        fsdp_plugin=plugin, mixed_precision="bf16",
        kwargs_handlers=[GradSyncKwargs(grad_dtype="bf16")],
    )
    params = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), _mlp_params())
    state = acc.create_train_state(params, acc.prepare_optimizer(recipe))
    step = acc.prepare_train_step(_mlp_loss, max_grad_norm=None)
    losses = []
    for batch in _batches(n=6):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, jax.device_get(state.params), jax.device_get(state.opt_state)


# ---------------------------------------------------------------------------
# Pipelined (double-buffered) chunked update — ops/streaming.py
# ---------------------------------------------------------------------------


def test_pipelined_offload_update_matches_serial_bitwise():
    """The 3-stage chunk pipeline (stage A per-chunk D2H, stage C per-chunk
    write-back, only the update regions token-serialized) is BITWISE
    identical to the fully serialized schedule: same chunk boundaries, same
    per-group math — the pipeline only reorders transfers.  adamw exercises
    the congruent-moment + shared-count slicing.

    Scope on this mesh: memory kinds degrade on CPU, so stage A slices the
    same values either way, but stage C's per-chunk placements DO run here
    (deliberately not gated on kinds_ok) — pipelined and serial trace
    genuinely different programs and must still agree bit-for-bit.  The
    pinned-host transfer legs are the on-chip concern
    (bench.py --pipeline on|off A/B)."""
    losses_ser, params_ser = _run(offload=True, chunk_gib=1e-6, pipeline=False)
    losses_pipe, params_pipe = _run(offload=True, chunk_gib=1e-6, pipeline=True)
    assert losses_pipe == losses_ser
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params_pipe, params_ser
    )


@pytest.mark.parametrize("recipe", ["lion-sr8", "adamw-sr8"])
def test_pipelined_offload_sr8_matches_serial_bitwise(recipe):
    """The SR-hash contract under the pipeline: -sr8 salts its SR streams
    with group-relative leaf indices, so identical chunk boundaries must
    give identical codes/scales/params no matter how the transfers are
    scheduled — pipelined == serial bit-for-bit, including the int8/uint8
    moment state."""
    losses_ser, params_ser, opt_ser = _run_sr8(recipe, offload=True,
                                               chunk_gib=1e-6, pipeline=False)
    losses_pipe, params_pipe, opt_pipe = _run_sr8(recipe, offload=True,
                                                  chunk_gib=1e-6, pipeline=True)
    assert losses_pipe == losses_ser
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params_pipe, params_ser
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), opt_pipe, opt_ser
    )


def test_pipelined_offload_with_clipping_matches_serial():
    """max_grad_norm forces the host-side global-norm barrier (stage A
    degrades to bulk staging); the pipeline must still match the serial
    schedule exactly."""
    losses_ser, params_ser = _run(offload=True, chunk_gib=1e-6, pipeline=False,
                                  max_grad_norm=1.0)
    losses_pipe, params_pipe = _run(offload=True, chunk_gib=1e-6, pipeline=True,
                                    max_grad_norm=1.0)
    assert losses_pipe == losses_ser
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params_pipe, params_ser
    )


@pytest.mark.parametrize("recipe", ["lion-sr8", "adamw-sr8"])
def test_offload_sr8_matches_resident_bitwise(recipe):
    """Bitwise expectation, documented: the -sr8 update is per-leaf
    deterministic (hashed SR keys from (count, leaf, value, grad) — no RNG
    state), so the host-compute offload run must reproduce the resident run
    EXACTLY: same losses, bit-identical bf16 params, bit-identical int8/uint8
    codes and fp32 scales.  Chunked grouping re-keys the per-leaf salts
    (group-relative leaf indices), so the chunked run is asserted to train,
    not to match bitwise."""
    losses_res, params_res, opt_res = _run_sr8(recipe, offload=False)
    losses_off, params_off, opt_off = _run_sr8(recipe, offload=True)
    assert jax.tree_util.tree_leaves(params_res)[0].dtype == jnp.bfloat16
    np.testing.assert_allclose(losses_off, losses_res, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), params_off, params_res
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), opt_off, opt_res
    )
    # the moment codes really are 8-bit storage
    assert opt_off.mu["dense"]["kernel"].dtype == jnp.int8
    if recipe == "adamw-sr8":
        assert opt_off.nu["dense"]["kernel"].dtype == jnp.uint8

    losses_chunk, params_chunk, _ = _run_sr8(recipe, offload=True, chunk_gib=1e-6)
    assert jax.tree_util.tree_leaves(params_chunk)[0].dtype == jnp.bfloat16
    assert np.isfinite(losses_chunk).all()
    # chunked offload must still land in the resident run's loss neighborhood
    np.testing.assert_allclose(losses_chunk, losses_res, rtol=0.35)
